"""Multi-tenant serving e2e (ISSUE 6): tenant routing over a real
trained engine, 429-vs-503 classification at the HTTP edge, transparent
cache eviction/reload, weighted-fair dispatch under a hog, per-tenant
fault scope, per-tenant canary rollouts, and mid-canary restart
re-adoption."""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import App
from predictionio_tpu.data.storage.registry import (
    SourceConfig,
    Storage,
    StorageConfig,
)
from predictionio_tpu.deploy.registry import ModelRegistry
from predictionio_tpu.resilience import faults
from predictionio_tpu.tenancy import Tenant, TenantMux, TenantStore
from predictionio_tpu.workflow.core import run_train
from predictionio_tpu.workflow.server import (
    QueryServer,
    QueryServerConfig,
    latest_completed_runtime,
)

VARIANT = {
    "id": "mtsrv",
    "engineFactory":
        "predictionio_tpu.engines.recommendation.RecommendationEngine",
    "datasource": {"params": {"app_name": "mtapp"}},
    "algorithms": [
        {"name": "als", "params": {"rank": 8, "num_iterations": 6}}
    ],
}


def _seed(storage, n_users=8):
    apps = storage.get_meta_data_apps()
    app_id = apps.insert(App(id=0, name="mtapp"))
    events = storage.get_events()
    events.init_app(app_id)
    rng = np.random.RandomState(0)
    batch = []
    for u in range(n_users):
        for _ in range(20):
            i = rng.randint(0, 5) + (u % 2) * 5
            batch.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties={"rating": 5.0},
            ))
    events.insert_batch(batch, app_id)


@pytest.fixture(scope="module")
def mt_storage(tmp_path_factory):
    """One sqlite+localfs storage with a trained model, shared by the
    module (training is the expensive part)."""
    tmp = tmp_path_factory.mktemp("tenancy_e2e")
    storage = Storage(StorageConfig(
        sources={
            "SQL": SourceConfig("SQL", "sqlite", {"PATH": str(tmp / "pio.db")}),
            "FS": SourceConfig("FS", "localfs", {"PATH": str(tmp)}),
        },
        repositories={
            "METADATA": "SQL", "EVENTDATA": "SQL", "MODELDATA": "FS",
        },
    ))
    _seed(storage)
    run_train(storage, VARIANT)
    return storage


def _make_server(storage, cache_capacity=2):
    runtime = latest_completed_runtime(storage, "mtsrv", "0", "mtsrv")
    srv = QueryServer(
        storage, runtime, QueryServerConfig(ip="127.0.0.1", port=0)
    )
    mux = TenantMux(
        storage, metrics=srv.metrics, cache_capacity=cache_capacity,
        refresh_s=0.0, sync_s=3600.0,
    )
    srv.attach_tenancy(mux)
    return srv, mux


@pytest.fixture()
def served(mt_storage):
    store = TenantStore(mt_storage)
    store.upsert(Tenant(id="t1", engine_id="mtsrv"))
    store.upsert(Tenant(id="t2", engine_id="mtsrv"))
    srv, mux = _make_server(mt_storage)
    port = srv.start()
    yield mt_storage, srv, mux, port
    srv.stop()


def post(port, path, body, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, dict(r.headers), json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read().decode() or "null")


def get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=30
    ) as r:
        return r.status, json.loads(r.read().decode())


# ---------------------------------------------------------------------------
# routing + control surface
# ---------------------------------------------------------------------------


def test_tenant_routing_paths_and_header(served):
    _, srv, mux, port = served
    status, _, body = post(
        port, "/tenants/t1/queries.json", {"user": "u0", "num": 3}
    )
    assert status == 200 and len(body["item_scores"]) == 3

    # header form routes the same way
    status, _, body = post(
        port, "/queries.json", {"user": "u1", "num": 2},
        headers={"X-PIO-Tenant": "t2"},
    )
    assert status == 200 and len(body["item_scores"]) == 2

    # unknown tenant is a 404, not a silent fall-through to the default
    status, _, body = post(
        port, "/tenants/ghost/queries.json", {"user": "u0"}
    )
    assert status == 404

    # the untenanted path still serves (single-tenant compatibility)
    status, _, body = post(port, "/queries.json", {"user": "u0", "num": 2})
    assert status == 200 and len(body["item_scores"]) == 2

    status, body = get(port, "/tenants")
    assert status == 200
    assert {"t1", "t2"} <= set(body["tenants"])
    assert body["cache"]["resident"] >= 1
    status, body = get(port, "/tenants/t1")
    assert status == 200 and body["resident"]
    # per-tenant serve metrics landed under the tenant label
    assert srv.metrics.histogram(
        "tenant_serve_seconds", labelnames=("tenant",)
    ).count_of(tenant="t1") >= 1


def test_quota_429_distinct_from_deadline_503(served):
    storage, srv, mux, port = served
    TenantStore(storage).upsert(
        Tenant(id="tq", engine_id="mtsrv", qps=1.0)
    )
    ok_status, _, _ = post(
        port, "/tenants/tq/queries.json", {"user": "u0", "num": 1}
    )
    assert ok_status == 200
    # burst is one second's allowance (1 token): the immediate second
    # request is over quota → 429 + Retry-After (the tenant's problem)
    status, headers, body = post(
        port, "/tenants/tq/queries.json", {"user": "u0", "num": 1}
    )
    assert status == 429
    assert int(headers.get("Retry-After", "0")) >= 1
    assert "quota" in body["message"]
    # an expired deadline on an IN-quota tenant is a 503 (the server
    # sheds; retry later) — the classifications must not blur
    status, headers, _ = post(
        port, "/tenants/t1/queries.json", {"user": "u0"},
        headers={"X-PIO-Deadline": "0"},
    )
    assert status == 503 and headers.get("Retry-After") == "1"
    # quota rejection is visible on the metrics surface
    assert srv.metrics.counter(
        "tenant_quota_rejected_total", labelnames=("tenant", "resource")
    ).value(tenant="tq", resource="qps") >= 1


def test_evicted_model_transparently_reloads(mt_storage):
    TenantStore(mt_storage).upsert(Tenant(id="t1", engine_id="mtsrv"))
    TenantStore(mt_storage).upsert(Tenant(id="t2", engine_id="mtsrv"))
    srv, mux = _make_server(mt_storage, cache_capacity=1)
    port = srv.start()
    try:
        assert post(port, "/tenants/t1/queries.json",
                    {"user": "u0", "num": 1})[0] == 200
        assert post(port, "/tenants/t2/queries.json",
                    {"user": "u1", "num": 1})[0] == 200  # evicts t1
        assert post(port, "/tenants/t1/queries.json",
                    {"user": "u0", "num": 1})[0] == 200  # reload, still 200
        assert post(port, "/tenants/t1/queries.json",
                    {"user": "u0", "num": 1})[0] == 200  # now a hit
        s = mux.cache.stats()
        assert s["capacity"] == 1
        assert s["evictions"] >= 2
        assert s["reloads"] >= 1
        assert s["hits"] >= 1
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# fairness under a hog (deterministic, dispatcher-level)
# ---------------------------------------------------------------------------


def test_hog_tenant_cannot_starve_good_tenant_dispatch():
    """80 queued hog queries + 8 good-tenant queries on one dispatcher:
    under DRR the good tenant's answers all land before the hog's
    median answer (under FIFO they would land after the hog's LAST)."""
    from concurrent.futures import Future

    from predictionio_tpu.workflow.server import _BatchDispatcher, _Pending

    class _SlowAlgo:
        serving_context = None

        def batch_predict(self, ctx, model, queries):
            time.sleep(0.02)  # the device is busy 20 ms per batch
            return [(i, q) for i, q in queries]

    class _Serving:
        def serve(self, q, preds):
            return preds[0]

    class _RT:  # one runtime object per tenant, like the model cache
        def __init__(self):
            self.algorithms = [_SlowAlgo()]
            self.models = [None]
            self.serving = _Serving()

    class _Owner:
        def bookkeep_predict(self, *_a):
            pass

        def tenant_weight(self, _t):
            return 1.0

    hog_rt, good_rt = _RT(), _RT()
    disp = _BatchDispatcher(
        _Owner(), window_ms=2.0, max_batch=8, max_window_ms=20.0,
        pipeline_depth=1,
    )
    try:
        done: dict = {}
        t_start = time.perf_counter()

        def enqueue(tenant, rt, i):
            fut: Future = Future()
            fut.add_done_callback(
                lambda _f, k=(tenant, i): done.setdefault(
                    k, time.perf_counter() - t_start
                )
            )
            disp._queue.put(_Pending(
                f"{tenant}-{i}", rt, fut, time.perf_counter(),
                (None, None), None, tenant,
            ))
            return fut

        hog = [enqueue("hog", hog_rt, i) for i in range(80)]
        good = [enqueue("good", good_rt, i) for i in range(8)]
        for f in hog + good:
            f.result(timeout=60)
        good_last = max(done[("good", i)] for i in range(8))
        hog_sorted = sorted(done[("hog", i)] for i in range(80))
        hog_median = hog_sorted[40]
        assert good_last < hog_median, (
            f"good tenant finished at {good_last:.3f}s, after the hog's "
            f"median {hog_median:.3f}s — starved"
        )
    finally:
        disp.stop()


# ---------------------------------------------------------------------------
# per-tenant fault scope
# ---------------------------------------------------------------------------


def test_per_tenant_fault_scope(served):
    _, _, _, port = served
    faults.install(faults.parse_spec(
        "dispatch.device@tenant/t1:error:1.0"
    ))
    try:
        status, _, _ = post(
            port, "/tenants/t1/queries.json", {"user": "u0", "num": 1}
        )
        assert status == 500  # only the targeted tenant breaks
        status, _, _ = post(
            port, "/tenants/t2/queries.json", {"user": "u1", "num": 1}
        )
        assert status == 200  # the neighbor sails through
    finally:
        faults.clear()


# ---------------------------------------------------------------------------
# per-tenant canary rollout + restart re-adoption
# ---------------------------------------------------------------------------


def test_per_tenant_rollout_and_abort(served):
    storage, srv, mux, port = served
    version = ModelRegistry(storage).register(srv.runtime.instance)
    status, _, body = post(port, "/tenants/t1/rollout/start", {
        "version": version.id, "fraction": 1.0,
        "min_requests": 10**9, "bake_s": 3600.0, "interval_s": 60.0,
    })
    assert status == 200 and body["state"] == "canary"

    # fraction 1.0: tenant t1's traffic serves from the candidate and
    # feeds its verdict window; t2 is untouched
    assert post(port, "/tenants/t1/queries.json",
                {"user": "u0", "num": 1})[0] == 200
    status, body = get(port, "/tenants/t1/rollout/status")
    assert status == 200 and body["state"] == "canary"
    assert body["candidate"]["count"] >= 1

    # conflicting second start → 409
    status, _, body = post(port, "/tenants/t1/rollout/start", {
        "version": version.id,
    })
    assert status == 409

    status, _, body = post(
        port, "/tenants/t1/rollout/abort", {"reason": "test cleanup"}
    )
    assert status == 200 and body["state"] == "aborted"
    assert ModelRegistry(storage).get(version.id).status == "rolled_back"
    # nothing left to abort → 409
    status, _, _ = post(port, "/tenants/t1/rollout/abort", {})
    assert status == 409
    # t1 serves live again
    assert post(port, "/tenants/t1/queries.json",
                {"user": "u0", "num": 1})[0] == 200


def test_rollout_survives_server_restart(mt_storage):
    """PR-5 follow-up satellite: a query-server restart mid-canary
    re-adopts the persisted rollout — same version, bake progress
    credited from the original wall-clock start."""
    runtime = latest_completed_runtime(mt_storage, "mtsrv", "0", "mtsrv")
    srv1 = QueryServer(
        mt_storage, runtime, QueryServerConfig(ip="127.0.0.1", port=0)
    )
    srv1.start()
    version = ModelRegistry(mt_storage).register(srv1.runtime.instance)
    srv1.start_rollout({
        "version": version.id, "fraction": 0.5,
        "min_requests": 10**9, "bake_s": 3600.0, "interval_s": 60.0,
    })
    assert srv1.rollout is not None and srv1.rollout.st.state == "canary"
    time.sleep(0.3)  # measurable bake progress to carry over
    srv1.stop()  # restart: verdict thread dies, record + registry stay

    runtime2 = latest_completed_runtime(mt_storage, "mtsrv", "0", "mtsrv")
    srv2 = QueryServer(
        mt_storage, runtime2, QueryServerConfig(ip="127.0.0.1", port=0)
    )
    port2 = srv2.start()
    try:
        rollout = srv2.rollout
        assert rollout is not None, "restart did not re-adopt the canary"
        assert rollout.st.state == "canary"
        assert rollout.st.version.id == version.id
        assert rollout.config.fraction == 0.5
        # bake progress carried over from the original start
        assert time.monotonic() - rollout.st.started_at >= 0.3
        assert srv2.candidate is not None
        # serving works with the re-adopted split
        status, _, body = post(
            port2, "/queries.json", {"user": "u0", "num": 1}
        )
        assert status == 200
        # terminal state persists: an aborted rollout is NOT re-adopted
        srv2.abort_rollout("test cleanup")
    finally:
        srv2.stop()
    srv3 = QueryServer(
        mt_storage,
        latest_completed_runtime(mt_storage, "mtsrv", "0", "mtsrv"),
        QueryServerConfig(ip="127.0.0.1", port=0),
    )
    srv3.start()
    try:
        assert srv3.rollout is None
    finally:
        srv3.stop()


def test_default_scope_start_still_flips_live_version_to_canary(mt_storage):
    """The tenant-scope live-skip in RolloutController.start() must NOT
    leak into the default scope: a server-scope canary of an
    already-live version flips it to "canary", because the default
    scope's resume path is strict (status must be "canary") and a
    skipped flip would make that bake unresumable after a restart."""
    runtime = latest_completed_runtime(mt_storage, "mtsrv", "0", "mtsrv")
    srv = QueryServer(
        mt_storage, runtime, QueryServerConfig(ip="127.0.0.1", port=0)
    )
    srv.start()
    try:
        registry = ModelRegistry(mt_storage)
        version = registry.register(srv.runtime.instance)
        registry.promote(version.id)
        srv.start_rollout({
            "version": version.id, "fraction": 0.5,
            "min_requests": 10**9, "bake_s": 3600.0, "interval_s": 60.0,
        })
        assert registry.get(version.id).status == "canary"
        srv.abort_rollout("test cleanup")
    finally:
        srv.stop()


def test_fallback_path_still_charges_device_seconds():
    """A tenant whose queries poison every batch (batch_predict raises,
    per-query fallback serves) must still be debited device-seconds —
    otherwise exactly the hog the quota exists to contain bypasses it."""
    from concurrent.futures import Future

    from predictionio_tpu.workflow.server import _BatchDispatcher, _Pending

    class _PoisonAlgo:
        serving_context = None

        def batch_predict(self, ctx, model, queries):
            raise RuntimeError("poison batch")

        def predict(self, model, q):
            time.sleep(0.005)  # real per-query device work
            return q

    class _Serving:
        def serve(self, q, preds):
            return preds[0]

    class _RT:
        def __init__(self):
            self.algorithms = [_PoisonAlgo()]
            self.models = [None]
            self.serving = _Serving()

    charges: dict = {}

    class _Owner:
        def bookkeep_predict(self, *_a):
            pass

        def tenant_weight(self, _t):
            return 1.0

        def charge_device_seconds(self, tid, s):
            charges[tid] = charges.get(tid, 0.0) + s

    disp = _BatchDispatcher(
        _Owner(), window_ms=2.0, max_batch=8, max_window_ms=20.0,
        pipeline_depth=1,
    )
    try:
        rt = _RT()
        futs = []
        for i in range(4):
            fut: Future = Future()
            disp._queue.put(_Pending(
                f"q{i}", rt, fut, time.perf_counter(), (None, None),
                None, "acme",
            ))
            futs.append(fut)
        for f in futs:
            assert f.result(timeout=30) is not None
        assert charges.get("acme", 0.0) >= 4 * 0.005
    finally:
        disp.stop()


def test_tenant_resume_survives_shared_version_promote(mt_storage):
    """Tenants of one engine canary the same trained version by default,
    so the version's GLOBAL status cannot prove THIS tenant's rollout
    finished: another tenant promoting it to "live" mid-bake must not
    cancel this tenant's restart re-adoption (and the resumed start must
    not clobber the live pointer back to "canary")."""
    store = TenantStore(mt_storage)
    store.upsert(Tenant(id="ta", engine_id="mtsrv"))
    srv, _mux = _make_server(mt_storage)
    port = srv.start()
    version = ModelRegistry(mt_storage).register(srv.runtime.instance)
    status, _, _ = post(port, "/tenants/ta/rollout/start", {
        "version": version.id, "fraction": 1.0,
        "min_requests": 10**9, "bake_s": 3600.0, "interval_s": 60.0,
    })
    assert status == 200
    srv.stop()  # restart mid-bake
    # meanwhile another tenant of the same engine promotes the shared
    # version: its global status flips to "live"
    ModelRegistry(mt_storage).promote(version.id)

    srv2, mux2 = _make_server(mt_storage)
    srv2.start()
    try:
        mux2.sync()
        # the first sync pass to claim re-adoption (ours or the mux's
        # background thread) builds the candidate runtime — poll
        host = None
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            host = mux2._hosts.get("ta")
            if host is not None and host.rollout is not None:
                break
            time.sleep(0.1)
        assert host is not None and host.rollout is not None, (
            "shared-version promote cancelled the tenant's re-adoption"
        )
        assert host.rollout.st.state == "canary"
        assert ModelRegistry(mt_storage).get(version.id).status == "live"
        host.rollout.stop()
        host.rollout.abort("test cleanup")
    finally:
        srv2.stop()
        store.delete("ta")


def test_tenant_resume_declines_rolled_back_and_retires_record(mt_storage):
    """A version rolled back elsewhere IS globally disqualifying — and
    the declined scope's stale "canary" record is retired so it is not
    re-considered (baseline warmed + pinned) on every restart forever."""
    from predictionio_tpu.deploy.registry import LifecycleRecordStore
    from predictionio_tpu.deploy.rollout import ROLLOUT_ENTITY

    store = TenantStore(mt_storage)
    store.upsert(Tenant(id="tb", engine_id="mtsrv"))
    srv, _mux = _make_server(mt_storage)
    port = srv.start()
    version = ModelRegistry(mt_storage).register(srv.runtime.instance)
    status, _, _ = post(port, "/tenants/tb/rollout/start", {
        "version": version.id, "fraction": 1.0,
        "min_requests": 10**9, "bake_s": 3600.0, "interval_s": 60.0,
    })
    assert status == 200
    srv.stop()
    ModelRegistry(mt_storage).rollback(version.id, "judged bad elsewhere")

    srv2, mux2 = _make_server(mt_storage)
    srv2.start()
    try:
        mux2.sync()
        # the declining sync pass may be the mux's background thread —
        # poll for the retired record it writes
        rec = None
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            rec = (
                LifecycleRecordStore(mt_storage)
                .fold(ROLLOUT_ENTITY, "tenant/tb")
                .get("tenant/tb")
            )
            if rec and rec.get("state") == "aborted":
                break
            time.sleep(0.1)
        host = mux2._hosts.get("tb")
        assert host is None or host.rollout is None
        assert rec and rec.get("state") == "aborted"
        assert "not resumed" in rec.get("verdict_reason", "")
    finally:
        srv2.stop()
        store.delete("tb")


def test_recreate_mid_canary_keeps_pinned_baseline(served):
    """Delete + recreate a tenant while its canary is still baking: the
    deferred cleanup must NOT invalidate the cache entry the rollout's
    pin lives on — a rebuilt baseline would be evictable mid-window."""
    storage, srv, mux, port = served
    version = ModelRegistry(storage).register(srv.runtime.instance)
    status, _, _ = post(port, "/tenants/t1/rollout/start", {
        "version": version.id, "fraction": 0.5,
        "min_requests": 10**9, "bake_s": 3600.0, "interval_s": 60.0,
    })
    assert status == 200
    baseline = mux.cache._entries.get("t1")
    assert baseline is not None and baseline.pinned

    store = TenantStore(storage)
    store.delete("t1")
    mux.refresh(force=True)  # delete observed; abort deferred (active)
    store.upsert(Tenant(id="t1", engine_id="mtsrv"))
    mux.refresh(force=True)  # recreate lands before the sync pass
    mux.sync()
    try:
        entry = mux.cache._entries.get("t1")
        assert entry is baseline, "recreate dropped the resident baseline"
        assert entry.pinned, "recreate unpinned the baking rollout's baseline"
        host = mux._hosts.get("t1")
        assert host is not None and host.rollout is not None
        assert host.rollout.st.state == "canary"
    finally:
        host = mux._hosts.get("t1")
        if host is not None and host.rollout is not None:
            host.rollout.stop()
            host.rollout.abort("test cleanup")


def test_tenant_deadline_floor_clamps_at_admit(served):
    """ISSUE 10 satellite: a tenant-level X-PIO-Deadline floor bounds
    how long its requests may live in the pipeline. A request with NO
    deadline gets the tenant's budget at admit — with a 1 ms floor and
    a 2 ms micro-batch window it must shed as a 503 instead of holding
    a dispatcher lease; the floor never LOOSENS a client's own tighter
    deadline, and floorless tenants are untouched."""
    storage, srv, mux, port = served
    TenantStore(storage).upsert(
        Tenant(id="tfloor", engine_id="mtsrv", deadline_floor_ms=1.0)
    )
    # no client deadline → clamped to the 1 ms floor → shed (503)
    status, headers, _ = post(
        port, "/tenants/tfloor/queries.json", {"user": "u0", "num": 1}
    )
    assert status == 503 and headers.get("Retry-After") == "1"
    # a generous floor admits normally
    TenantStore(storage).set_quota("tfloor", deadline_floor_ms=30_000)
    status, _, body = post(
        port, "/tenants/tfloor/queries.json", {"user": "u0", "num": 1}
    )
    assert status == 200
    # the client's own TIGHTER (already expired) deadline still wins
    status, _, _ = post(
        port, "/tenants/tfloor/queries.json", {"user": "u0"},
        headers={"X-PIO-Deadline": "0"},
    )
    assert status == 503
    # floorless tenants never see a clamp
    status, _, _ = post(
        port, "/tenants/t1/queries.json", {"user": "u0", "num": 1}
    )
    assert status == 200
