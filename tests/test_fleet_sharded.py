"""Fleet sharded serving + mp-sharded training (ISSUE 10).

Runs on the conftest's 8 virtual CPU devices
(XLA_FLAGS=--xla_force_host_platform_device_count=8); every test skips
cleanly when the mesh isn't available. The correctness contract:
model-axis sharding must be INVISIBLE in results — mp-sharded train
matches the single-device solve (same tolerance as the existing
sharded-vs-dense checks), sharded top-k matches dense top-k exactly.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

if len(jax.devices()) < 8:  # pragma: no cover - env guard
    pytest.skip(
        "needs 8 devices (xla_force_host_platform_device_count)",
        allow_module_level=True,
    )

from predictionio_tpu.models import als  # noqa: E402
from predictionio_tpu.parallel.mesh import MeshConf  # noqa: E402


@pytest.fixture(scope="module")
def coo():
    rng = np.random.RandomState(11)
    n_u, n_i = 300, 180
    keys = np.unique(rng.randint(0, n_u * n_i, 4000))
    rows = (keys // n_i).astype(np.int32)
    cols = (keys % n_i).astype(np.int32)
    vals = np.float32(1.0) + (keys % 5).astype(np.float32)
    return rows, cols, vals, n_u, n_i


@pytest.fixture(scope="module")
def factors():
    rng = np.random.RandomState(0)
    uf = rng.randn(137, 16).astype(np.float32)
    itf = rng.randn(211, 16).astype(np.float32)
    return uf, itf


class TestMpShardedDenseTrain:
    """Model-axis sharded dense ALS == the single-device solve."""

    @pytest.mark.parametrize("dp,mp", [(4, 2), (2, 4), (1, 8)])
    @pytest.mark.parametrize("implicit", [True, False])
    def test_mp_sharded_matches_single_device(self, coo, dp, mp, implicit):
        rows, cols, vals, n_u, n_i = coo
        p = als.ALSParams(
            rank=8, iterations=3, cg_iterations=3, implicit_prefs=implicit
        )
        single = als.stage_dense(
            rows, cols, vals, n_u, n_i, p, dense_dtype="f32"
        )
        uf1, itf1 = single.factors(*single.run())
        mesh = MeshConf(dp=dp, mp=mp).build()
        sharded = als.stage_dense(
            rows, cols, vals, n_u, n_i, p, dense_dtype="f32", mesh=mesh
        )
        uf2, itf2 = sharded.factors(*sharded.run())
        # same tolerance as TestDenseSharded's dp-only parity check
        np.testing.assert_allclose(uf2, uf1, rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(itf2, itf1, rtol=1e-3, atol=1e-4)

    def test_mp_sharded_warm_start_matches(self, coo):
        """init_factors ride the mp shardings (warm-started periodic
        retrains must work sharded too)."""
        rows, cols, vals, n_u, n_i = coo
        p = als.ALSParams(rank=8, iterations=2, cg_iterations=3)
        rng = np.random.RandomState(7)
        init = (
            rng.randn(n_u, 8).astype(np.float32),
            rng.randn(n_i, 8).astype(np.float32),
        )
        single = als.stage_dense(
            rows, cols, vals, n_u, n_i, p, dense_dtype="f32",
            init_factors=init,
        )
        uf1, itf1 = single.factors(*single.run())
        mesh = MeshConf(dp=2, mp=4).build()
        sharded = als.stage_dense(
            rows, cols, vals, n_u, n_i, p, dense_dtype="f32", mesh=mesh,
            init_factors=init,
        )
        uf2, itf2 = sharded.factors(*sharded.run())
        np.testing.assert_allclose(uf2, uf1, rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(itf2, itf1, rtol=1e-3, atol=1e-4)

    def test_train_api_dispatches_mp_mesh(self, coo, monkeypatch):
        """The public als.train under an mp>1 mesh returns well-formed,
        finite factors (the engine.json `mesh` key path)."""
        monkeypatch.setenv("PIO_DENSE_ALS", "1")
        rows, cols, vals, n_u, n_i = coo
        m = als.train(
            rows, cols, vals, n_u, n_i,
            als.ALSParams(rank=6, iterations=2),
            mesh=MeshConf(dp=2, mp=4).build(),
        )
        assert m.user_factors.shape == (n_u, 6)
        assert np.all(np.isfinite(m.user_factors))
        assert np.all(np.isfinite(m.item_factors))


class TestShardedRuntime:
    """Sharded serving: local top-k per shard + global merge must equal
    the dense single-device answer bit-for-bit (scores are the same
    dot products; only the selection is distributed)."""

    def _runtime(self, factors, **kw):
        from predictionio_tpu.fleet import ShardedRuntime

        uf, itf = factors
        return ShardedRuntime(uf, itf, **kw)

    def test_recommend_matches_dense(self, factors):
        uf, itf = factors
        srt = self._runtime(factors)
        assert srt.n_shards == 8
        m = als.ALSFactors(uf, itf, None, None)
        rows = np.array([0, 5, 88, 136], np.int64)
        v0, i0 = als.recommend(m, rows, 17)
        v1, i1 = srt.recommend(rows, 17)
        np.testing.assert_allclose(v1, v0, rtol=1e-5, atol=1e-6)
        assert (i1 == i0).all()

    def test_recommend_masked_matches_dense(self, factors):
        uf, itf = factors
        srt = self._runtime(factors)
        m = als.ALSFactors(uf, itf, None, None)
        rows = np.array([3, 77], np.int64)
        mask = np.zeros((2, itf.shape[0]), bool)
        mask[0, :50] = True
        mask[1, ::3] = True
        v0, i0 = als.recommend(m, rows, 9, exclude_mask=mask)
        v1, i1 = srt.recommend(rows, 9, exclude_mask=mask)
        np.testing.assert_allclose(v1, v0, rtol=1e-5, atol=1e-6)
        assert (i1 == i0).all()

    def test_similar_matches_dense(self, factors):
        uf, itf = factors
        srt = self._runtime(factors)
        m = als.ALSFactors(uf, itf, None, None)
        rows = np.array([1, 9, 210], np.int64)
        v0, i0 = als.similar_items(m, rows, 7)
        v1, i1 = srt.similar_items(rows, 7)
        np.testing.assert_allclose(v1, v0, rtol=1e-4, atol=1e-5)
        assert (i1 == i0).all()

    def test_fold_in_matches_dense(self, factors):
        uf, itf = factors
        srt = self._runtime(factors)
        p = als.ALSParams(rank=uf.shape[1], implicit_prefs=True)
        edges = [
            [(3, 4.0), (7, 1.0)],
            [(110, 2.0)],
            [(0, 5.0), (1, 1.0), (2, 3.0), (205, 2.0)],
        ]
        s0 = als.fold_in_rows(itf, edges, p)
        s1 = srt.fold_in_rows(edges, p, side="user")
        np.testing.assert_allclose(s1, s0, rtol=1e-4, atol=1e-5)
        # item side folds against the user matrix
        p2 = als.ALSParams(rank=uf.shape[1], implicit_prefs=False)
        edges_i = [[(5, 3.0), (9, 4.0)]]
        s0 = als.fold_in_rows(uf, edges_i, p2)
        s1 = srt.fold_in_rows(edges_i, p2, side="item")
        np.testing.assert_allclose(s1, s0, rtol=1e-4, atol=1e-5)

    def test_update_rows_visible_in_topk(self, factors):
        srt = self._runtime(factors)
        boosted = np.full((1, srt.rank), 10.0, np.float32)
        srt.update_item_rows(np.array([42]), boosted)
        q = np.full((1, srt.rank), 1.0, np.float32)
        srt.update_user_rows(np.array([0]), q)
        _, idx = srt.recommend(np.array([0]), 1)
        assert idx[0, 0] == 42

    def test_oversized_catalog_refused_single_device(self, factors):
        """The tentpole proof shape: a catalog whose factor state
        exceeds one device's budget — the single-device gate refuses,
        the 8-shard runtime loads (per-shard slice fits) and serves."""
        from predictionio_tpu.fleet import (
            OversizedModelError,
            ShardedRuntime,
            check_single_device_budget,
            factor_state_bytes,
        )

        uf, itf = factors
        total = factor_state_bytes(uf.shape[0], itf.shape[0], uf.shape[1])
        budget = total / 4  # one "chip" fits a quarter of the catalog
        with pytest.raises(OversizedModelError):
            check_single_device_budget(
                uf.shape[0], itf.shape[0], uf.shape[1], budget
            )
        srt = ShardedRuntime(uf, itf, device_budget_bytes=budget)
        m = als.ALSFactors(uf, itf, None, None)
        rows = np.array([4, 9], np.int64)
        v0, i0 = als.recommend(m, rows, 5)
        v1, i1 = srt.recommend(rows, 5)
        np.testing.assert_allclose(v1, v0, rtol=1e-5, atol=1e-6)
        assert (i1 == i0).all()
        # a budget even the per-shard slice cannot fit refuses too
        with pytest.raises(OversizedModelError):
            ShardedRuntime(
                uf, itf, device_budget_bytes=total / (8 * 4)
            )

    def test_per_shard_device_bytes(self, factors):
        srt = self._runtime(factors)
        b = srt.device_bytes()
        assert b["shards"] == 8
        assert b["per_shard"] == pytest.approx(b["total"] / 8)

    def test_cache_accounting_counts_addressable_shard(self, factors):
        """tenancy.cache's device-bytes walk must charge a sharded
        runtime its per-device shard, not the global catalog."""
        from predictionio_tpu.tenancy.cache import (
            estimate_runtime_device_bytes,
        )

        srt = self._runtime(factors)

        class RT:
            models = (srt,)

        per_dev = estimate_runtime_device_bytes(RT())
        assert per_dev == pytest.approx(
            srt.device_bytes()["total"] / 8, rel=1e-6
        )


class TestEngineShardServing:
    def test_predict_batch_matches_dense_path(self, factors):
        from predictionio_tpu.data.store.bimap import BiMap
        from predictionio_tpu.engines.recommendation.engine import (
            ALSAlgorithm,
            ALSAlgorithmParams,
            ALSModel,
            Query,
        )

        uf, itf = factors
        uv = BiMap({f"u{i}": i for i in range(uf.shape[0])})
        iv = BiMap({f"i{i}": i for i in range(itf.shape[0])})
        fs = als.ALSFactors(uf, itf, uv, iv, als.ALSParams(rank=uf.shape[1]))
        qs = [
            Query(user="u3", num=5),
            Query(user="u17", num=5, blacklist=["i0", "i1"]),
            Query(user="nope", num=5),  # unknown user → empty result
        ]
        dense = ALSAlgorithm(ALSAlgorithmParams(rank=uf.shape[1]))
        shard = ALSAlgorithm(
            ALSAlgorithmParams(rank=uf.shape[1], shard_serving=True)
        )
        r0 = dense._predict_batch(ALSModel(fs), qs)
        model = ALSModel(fs)
        r1 = shard._predict_batch(model, qs)
        assert model.sharded_info() is not None
        assert model.sharded_info()["shards"] == 8
        for a, b in zip(r0, r1):
            assert [
                (s.item, round(s.score, 4)) for s in a.item_scores
            ] == [(s.item, round(s.score, 4)) for s in b.item_scores]
