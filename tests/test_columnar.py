"""BiMap + EventFrame columnar loader tests
(reference analogues: BiMapSpec incl. RDD stringLong; the PEvents read path)."""

import datetime as dt

import numpy as np
import pytest

from predictionio_tpu.data import DataMap, Event
from predictionio_tpu.data.storage.base import EventQuery
from predictionio_tpu.data.storage.sqlite import SqliteEventStore
from predictionio_tpu.data.store.bimap import BiMap, EntityMap
from predictionio_tpu.data.store.columnar import EventFrame

UTC = dt.timezone.utc


def T(i):
    return dt.datetime(2024, 1, 1, tzinfo=UTC) + dt.timedelta(minutes=i)


def rate(u, i, r, t=0):
    return Event(
        event="rate",
        entity_type="user",
        entity_id=u,
        target_entity_type="item",
        target_entity_id=i,
        properties=DataMap({"rating": r}),
        event_time=T(t),
    )


class TestBiMap:
    def test_basic(self):
        m = BiMap({"a": 1, "b": 2})
        assert m("a") == 1
        assert m.inverse()(2) == "b"
        assert "a" in m and "z" not in m
        assert m.get("z", -1) == -1

    def test_duplicate_values_rejected(self):
        with pytest.raises(ValueError):
            BiMap({"a": 1, "b": 1})

    def test_string_int(self):
        m = BiMap.string_int(["x", "y", "x", "z"])
        assert len(m) == 3
        assert m("x") == 0 and m("y") == 1 and m("z") == 2

    def test_map_array(self):
        m = BiMap.string_int(["x", "y"])
        out = m.map_array(["y", "x", "missing"])
        np.testing.assert_array_equal(out, [1, 0, -1])

    def test_take(self):
        m = BiMap.string_int(["a", "b", "c"])
        assert set(m.take(["a", "c", "zz"]).to_dict()) == {"a", "c"}

    def test_entity_map(self):
        em = EntityMap({"u1": {"x": 1}, "u2": {"x": 2}})
        assert em["u1"] == {"x": 1}
        assert em.entity_of(em.index_of("u2")) == "u2"
        assert len(em) == 2


class TestEventFrame:
    def test_from_events(self):
        frame = EventFrame.from_events(
            [rate("u1", "i1", 4.0), rate("u2", "i1", 3.0, t=1), rate("u1", "i2", 5.0, t=2)],
            value_prop="rating",
        )
        assert len(frame) == 3
        assert frame.n_entities == 2
        assert frame.n_targets == 2
        np.testing.assert_allclose(frame.value, [4.0, 3.0, 5.0])
        assert frame.entity_type == "user"
        assert frame.target_entity_type == "item"

    def test_missing_value_prop_default(self):
        e = Event(
            event="view", entity_type="user", entity_id="u1",
            target_entity_type="item", target_entity_id="i1", event_time=T(0),
        )
        frame = EventFrame.from_events([e], value_prop="rating", default_value=1.5)
        np.testing.assert_allclose(frame.value, [1.5])

    def test_where_event_and_time(self):
        events = [
            rate("u1", "i1", 4.0, t=0),
            Event(event="view", entity_type="user", entity_id="u1",
                  target_entity_type="item", target_entity_id="i2", event_time=T(1)),
        ]
        frame = EventFrame.from_events(events)
        assert len(frame.where_event("rate")) == 1
        assert len(frame.where_event("nope")) == 0
        assert len(frame.where_time(start=T(1))) == 1

    def test_interactions_sum_dedupe(self):
        frame = EventFrame.from_events(
            [rate("u1", "i1", 2.0, t=0), rate("u1", "i1", 3.0, t=1), rate("u2", "i2", 1.0, t=2)],
            value_prop="rating",
        )
        rows, cols, vals = frame.interactions(dedupe="sum")
        got = {(int(r), int(c)): float(v) for r, c, v in zip(rows, cols, vals)}
        u1, u2 = frame.entity_vocab("u1"), frame.entity_vocab("u2")
        i1, i2 = frame.target_vocab("i1"), frame.target_vocab("i2")
        assert got[(u1, i1)] == 5.0
        assert got[(u2, i2)] == 1.0

    def test_interactions_last_dedupe(self):
        frame = EventFrame.from_events(
            [rate("u1", "i1", 2.0, t=0), rate("u1", "i1", 3.0, t=5)],
            value_prop="rating",
        )
        rows, cols, vals = frame.interactions(dedupe="last")
        assert len(vals) == 1 and vals[0] == 3.0

    def test_events_without_target_excluded(self):
        events = [
            rate("u1", "i1", 4.0),
            Event(event="signup", entity_type="user", entity_id="u3", event_time=T(1)),
        ]
        rows, cols, vals = EventFrame.from_events(events).interactions()
        assert len(rows) == 1

    def test_counts_per_entity(self):
        frame = EventFrame.from_events(
            [rate("u1", "i1", 1), rate("u1", "i2", 1, t=1), rate("u2", "i1", 1, t=2)]
        )
        counts = frame.counts_per_entity()
        assert counts[frame.entity_vocab("u1")] == 2
        assert counts[frame.entity_vocab("u2")] == 1


class TestSqliteColumnarPath:
    def test_find_frame_matches_generic(self, tmp_path):
        store = SqliteEventStore({"PATH": str(tmp_path / "ev.db")})
        store.init_app(1)
        events = [rate(f"u{i%7}", f"i{i%11}", float(i % 5 + 1), t=i) for i in range(100)]
        store.insert_batch(events, 1)
        q = EventQuery(app_id=1, event_names=["rate"])
        fast = store.find_frame(q, value_prop="rating")
        slow = EventFrame.from_events(store.find(q), value_prop="rating")
        assert len(fast) == len(slow) == 100
        np.testing.assert_allclose(np.sort(fast.value), np.sort(slow.value))
        fr, fc, fv = fast.interactions()
        sr, sc, sv = slow.interactions()
        assert fv.sum() == pytest.approx(sv.sum())
        assert fast.n_entities == 7 and fast.n_targets == 11

    def test_find_frame_empty(self, tmp_path):
        store = SqliteEventStore({"PATH": str(tmp_path / "ev.db")})
        store.init_app(1)
        frame = store.find_frame(EventQuery(app_id=1))
        assert len(frame) == 0


class TestFacade:
    def test_find_and_frame_by_app_name(self, fresh_storage):
        from predictionio_tpu.data.storage.base import App
        from predictionio_tpu.data.store.event_store import EventStoreFacade

        apps = fresh_storage.get_meta_data_apps()
        app_id = apps.insert(App(0, "testapp"))
        store = fresh_storage.get_events()
        store.init_app(app_id)
        store.insert_batch([rate("u1", "i1", 4.0), rate("u2", "i2", 2.0, t=1)], app_id)

        facade = EventStoreFacade(fresh_storage)
        found = list(facade.find("testapp", event_names=["rate"]))
        assert len(found) == 2
        frame = facade.find_frame("testapp", event_names=["rate"], value_prop="rating")
        assert len(frame) == 2
        by_entity = list(facade.find_by_entity("testapp", "user", "u1"))
        assert len(by_entity) == 1

    def test_unknown_app(self, fresh_storage):
        from predictionio_tpu.data.storage.base import StorageError
        from predictionio_tpu.data.store.event_store import EventStoreFacade

        with pytest.raises(StorageError):
            EventStoreFacade(fresh_storage).find("nope")
