"""Mid-training checkpointing (VERDICT r1 #10): segmented warm-started
ALS must reproduce an uninterrupted run, and a killed train must resume
from its MODELDATA snapshot."""

import numpy as np
import pytest

from predictionio_tpu.models import als
from predictionio_tpu.workflow.checkpoint import (
    CheckpointManager,
    train_als_checkpointed,
)


@pytest.fixture()
def data():
    rng = np.random.RandomState(5)
    n_users, n_items, n_edges = 50, 30, 600
    rows = rng.randint(0, n_users, n_edges).astype(np.int32)
    cols = rng.randint(0, n_items, n_edges).astype(np.int32)
    vals = (rng.rand(n_edges) * 4 + 1).astype(np.float32)
    return rows, cols, vals, n_users, n_items


PARAMS = als.ALSParams(rank=6, iterations=9, implicit_prefs=True)


def test_warm_start_segments_equal_uninterrupted(data):
    rows, cols, vals, u, i = data
    full = als.train(rows, cols, vals, u, i, PARAMS)
    # 9 iterations as 4 + 4 + 1 with explicit warm starts
    seg = als.train(
        rows, cols, vals, u, i,
        als.ALSParams(rank=6, iterations=4, implicit_prefs=True),
    )
    seg = als.train(
        rows, cols, vals, u, i,
        als.ALSParams(rank=6, iterations=4, implicit_prefs=True),
        init_factors=(seg.user_factors, seg.item_factors),
    )
    seg = als.train(
        rows, cols, vals, u, i,
        als.ALSParams(rank=6, iterations=1, implicit_prefs=True),
        init_factors=(seg.user_factors, seg.item_factors),
    )
    np.testing.assert_allclose(
        full.user_factors, seg.user_factors, rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(
        full.item_factors, seg.item_factors, rtol=1e-5, atol=1e-6
    )


def test_kill_and_resume_matches_uninterrupted(data, fresh_storage):
    rows, cols, vals, u, i = data
    full = als.train(rows, cols, vals, u, i, PARAMS)

    manager = CheckpointManager(fresh_storage, "inst-1")
    killed = {"count": 0}

    class Killed(RuntimeError):
        pass

    def die_after_two_segments(done):
        killed["count"] += 1
        if killed["count"] == 2:
            raise Killed()

    with pytest.raises(Killed):
        train_als_checkpointed(
            rows, cols, vals, u, i, PARAMS, manager,
            checkpoint_every=3, on_segment=die_after_two_segments,
        )
    # a snapshot at iteration 6 survives the crash
    loaded = manager.load()
    assert loaded is not None and loaded[0] == 6

    resumed = train_als_checkpointed(
        rows, cols, vals, u, i, PARAMS, manager, checkpoint_every=3
    )
    np.testing.assert_allclose(
        full.user_factors, resumed.user_factors, rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(
        full.item_factors, resumed.item_factors, rtol=1e-5, atol=1e-6
    )
    assert manager.load() is None  # cleared on success


def test_checkpointing_disabled_is_plain_train(data):
    rows, cols, vals, u, i = data
    a = train_als_checkpointed(
        rows, cols, vals, u, i, PARAMS, None, checkpoint_every=0
    )
    b = als.train(rows, cols, vals, u, i, PARAMS)
    np.testing.assert_array_equal(a.user_factors, b.user_factors)


def test_engine_level_checkpointing(fresh_storage):
    """engine.json-driven: checkpoint_every flows through run_train; the
    completed train leaves no stale checkpoint behind."""
    from predictionio_tpu.data.event import Event
    from predictionio_tpu.data.storage.base import App
    from predictionio_tpu.workflow.core import run_train

    app_id = fresh_storage.get_meta_data_apps().insert(App(id=0, name="ckapp"))
    fresh_storage.get_events().init_app(app_id)
    rng = np.random.RandomState(0)
    fresh_storage.get_events().insert_batch(
        [
            Event(
                event="rate", entity_type="user", entity_id=f"u{rng.randint(8)}",
                target_entity_type="item", target_entity_id=f"i{rng.randint(6)}",
                properties={"rating": float(rng.randint(1, 6))},
            )
            for _ in range(60)
        ],
        app_id,
    )
    variant = {
        "id": "ck",
        "engineFactory":
            "predictionio_tpu.engines.recommendation.RecommendationEngine",
        "datasource": {"params": {"app_name": "ckapp"}},
        "algorithms": [
            {
                "name": "als",
                "params": {
                    "rank": 4, "num_iterations": 6, "checkpoint_every": 2,
                },
            }
        ],
    }
    inst = run_train(fresh_storage, variant)
    assert inst.status == "COMPLETED"
    assert fresh_storage.get_model_data_models().get(f"ckpt:{inst.id}") is None
    assert fresh_storage.get_model_data_models().get(inst.id) is not None
