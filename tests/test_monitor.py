"""Monitoring plane unit coverage (ISSUE 8): TSDB ring semantics +
counter-reset rates, sampler output shape, exposition parsing, fleet
scrape, SLO burn-rate math (window edges, zero traffic, hysteresis),
alert state machine, thread hygiene, trace capture, devprof loop
calibration, and the HBM-byte-bounded tenant cache."""

import threading
import time

import numpy as np
import pytest

from predictionio_tpu.obs.monitor import (
    FleetScraper,
    Monitor,
    SLOEngine,
    SLOSpec,
    load_slos,
    parse_prometheus_text,
    parse_targets,
    sample_families,
)
from predictionio_tpu.obs.monitor.tsdb import (
    TSDB,
    MetricsSampler,
    increase_of,
    quantile_of,
)
from predictionio_tpu.obs.registry import MetricsRegistry

T0 = 1_700_000_000.0  # fixed epoch base: every test drives time explicitly


# ---------------------------------------------------------------------------
# TSDB core
# ---------------------------------------------------------------------------


class TestTSDB:
    def test_ring_wraparound_keeps_newest(self):
        db = TSDB(capacity=4)
        for i in range(10):
            db.add("m", None, float(i), "gauge", t=T0 + i)
        (series,) = db.matching("m")
        pts = db.points(series)
        assert len(pts) == 4
        assert [v for _t, v in pts] == [6.0, 7.0, 8.0, 9.0]

    def test_increase_survives_counter_reset(self):
        # 10 → 2 is a restart: the post-reset value IS the delta
        assert increase_of([(0, 10.0), (1, 2.0), (2, 5.0)]) == 5.0
        assert increase_of([(0, 3.0)]) == 0.0
        assert increase_of([]) == 0.0

    def test_increase_and_rate_over_window(self):
        db = TSDB()
        for i in range(11):
            db.add("c", {"k": "a"}, float(i * 5), "counter", t=T0 + i)
        now = T0 + 10
        # in-window points are t5..t10 (edge inclusive); the last
        # pre-window sample (t4, value 20) is the baseline — the delta
        # into the window is attributed to it: 50 - 20 = 30
        assert db.increase("c", {"k": "a"}, window_s=5, now=now) == 30.0
        assert db.rate("c", {"k": "a"}, window_s=5, now=now) == 6.0
        # a window past all points sees the full increase (no baseline)
        assert db.increase("c", {"k": "a"}, window_s=1e6, now=now) == 50.0
        # a window before any point sees nothing
        assert db.increase("c", {"k": "a"}, window_s=5, now=now + 100) == 0.0

    def test_increase_single_sample_window_uses_baseline(self):
        # sparse sampling: one in-window sample must still show the
        # increase from the last pre-window sample (the window-edge bug
        # the SLO engine's resolve path depends on)
        db = TSDB()
        db.add("c", None, 10.0, "counter", t=T0)
        db.add("c", None, 60.0, "counter", t=T0 + 100)
        assert db.increase("c", window_s=10, now=T0 + 105) == 50.0

    def test_label_matching_is_subset(self):
        db = TSDB()
        db.add("m", {"a": "1", "b": "2"}, 1.0, t=T0)
        db.add("m", {"a": "1", "b": "3"}, 2.0, t=T0)
        db.add("other", {"a": "1"}, 9.0, t=T0)
        assert len(db.matching("m", {"a": "1"})) == 2
        assert len(db.matching("m", {"b": "3"})) == 1
        assert len(db.matching("m", {"b": "9"})) == 0
        assert len(db.matching("m")) == 2

    def test_cardinality_cap_drops_new_series(self):
        db = TSDB(max_series=2)
        assert db.add("a", None, 1.0, t=T0)
        assert db.add("b", None, 1.0, t=T0)
        assert not db.add("c", None, 1.0, t=T0)
        # existing series still accept points past the cap
        assert db.add("a", None, 2.0, t=T0 + 1)
        assert db.dropped_series == 1
        assert db.series_count() == 2

    def test_quantile_over_time(self):
        db = TSDB()
        for i in range(100):
            db.add("g", None, float(i), "gauge", t=T0 + i)
        now = T0 + 99
        assert db.quantile_over_time("g", 1.0, now=now) == 99.0
        p50 = db.quantile_over_time("g", 0.5, window_s=19, now=now)
        assert 89.0 <= p50 <= 91.0
        assert db.quantile_over_time("missing", 0.5) is None
        assert quantile_of([5.0], 0.99) == 5.0

    def test_summary_shape(self):
        db = TSDB()
        db.add("m", {"x": "1"}, 7.0, "counter", t=T0)
        summary = db.summary()
        assert summary["series_count"] == 1
        row = summary["series"][0]
        assert row["name"] == "m" and row["last"] == 7.0
        assert row["kind"] == "counter" and row["labels"] == {"x": "1"}


# ---------------------------------------------------------------------------
# sampler
# ---------------------------------------------------------------------------


class TestSampler:
    def test_sample_families_counters_gauges_histograms(self):
        reg = MetricsRegistry()
        reg.counter("hits_total", "", ("k",)).inc(3, k="a")
        reg.gauge("depth").set(2.5)
        reg.gauge_callback("cb", "", lambda: 42.0)
        h = reg.histogram("lat_seconds", "", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        h.observe(5.0)
        db = TSDB()
        sample_families(db, reg.families(), now=T0)
        assert db.latest("hits_total", {"k": "a"}) == 3.0
        assert db.latest("depth") == 2.5
        assert db.latest("cb") == 42.0
        assert db.latest("lat_seconds_count") == 3.0
        # cumulative buckets: le=0.1 → 1, le=1.0 → 2, +Inf → 3
        assert db.latest("lat_seconds_bucket", {"le": "0.1"}) == 1.0
        assert db.latest("lat_seconds_bucket", {"le": "1.0"}) == 2.0
        assert db.latest("lat_seconds_bucket", {"le": "+Inf"}) == 3.0
        assert db.latest("lat_seconds", {"quantile": "p50"}) is not None

    def test_sampler_thread_joins_on_stop(self):
        reg = MetricsRegistry()
        reg.counter("c").inc()
        db = TSDB()
        sampler = MetricsSampler(db, reg.families, interval_s=0.05)
        sampler.start()
        deadline = time.monotonic() + 5
        while db.latest("c") is None and time.monotonic() < deadline:
            time.sleep(0.01)
        assert db.latest("c") == 1.0
        sampler.stop()
        assert not any(
            t.name == "tsdb-sampler" for t in threading.enumerate()
        )


# ---------------------------------------------------------------------------
# exposition parsing + fleet scrape
# ---------------------------------------------------------------------------


class TestScrape:
    def test_parse_targets(self):
        assert parse_targets("a=http://h:1, b=http://h:2/") == [
            ("a", "http://h:1"), ("b", "http://h:2"),
        ]
        assert parse_targets("http://h:3") == [("h:3", "http://h:3")]
        assert parse_targets("") == []

    def test_parse_prometheus_roundtrip(self):
        reg = MetricsRegistry()
        reg.counter("c_total", "help", ("path",)).inc(
            2, path='/x"y\\z\nw'
        )
        reg.gauge("g").set(1.5)
        reg.histogram("h_seconds", "", buckets=(1.0,)).observe(0.5)
        samples = parse_prometheus_text(reg.render())
        by_name = {}
        for name, labels, value in samples:
            by_name.setdefault(name, []).append((labels, value))
        assert by_name["c_total"] == [({"path": '/x"y\\z\nw'}, 2.0)]
        assert by_name["g"] == [({}, 1.5)]
        assert ({"le": "1"}, 1.0) in by_name["h_seconds_bucket"]
        assert ({"le": "+Inf"}, 1.0) in by_name["h_seconds_bucket"]

    def test_scraper_tags_instance_and_up(self, fresh_storage):
        from predictionio_tpu.data.api.server import (
            EventServer,
            EventServerConfig,
        )

        srv = EventServer(
            fresh_storage,
            EventServerConfig(ip="127.0.0.1", port=0, wal_dir=None),
        )
        port = srv.start()
        db = TSDB()
        scraper = FleetScraper(
            db,
            [("ev", f"http://127.0.0.1:{port}"),
             ("dead", "http://127.0.0.1:1")],
            interval_s=60,
        )
        try:
            ups = scraper.scrape_once()
        finally:
            srv.stop()
        assert ups == {"ev": True, "dead": False}
        assert db.latest("up", {"instance": "ev"}) == 1.0
        assert db.latest("up", {"instance": "dead"}) == 0.0
        assert db.latest(
            "scrape_duration_seconds", {"instance": "dead"}
        ) is not None
        # scraped series carry the instance tag
        assert db.matching("events_shed_total") == []  # nothing bogus
        assert any(
            s.labels_dict().get("instance") == "ev"
            for s in db.matching("http_requests_total")
        ) or db.latest("scrape_samples_stored", {"instance": "ev"}) >= 0
        status = {t["instance"]: t for t in scraper.status()}
        assert status["dead"]["up"] is False
        scraper.stop()  # never started: stop is a no-op, not an error


# ---------------------------------------------------------------------------
# SLO burn-rate math + alert state machine
# ---------------------------------------------------------------------------


def _avail_spec(**kw) -> SLOSpec:
    base = dict(
        name="avail", kind="availability", objective=0.99,
        server="query", route="/queries.json",
        fast_window_s=10.0, window_s=40.0, burn_threshold=1.0,
        min_samples=1,
    )
    base.update(kw)
    return SLOSpec(**base)


def _feed_availability(db: TSDB, t: float, ok: float, err: float) -> None:
    db.add(
        "http_requests_total",
        {"server": "query", "path": "/queries.json", "status": "200"},
        ok, "counter", t=t,
    )
    db.add(
        "http_requests_total",
        {"server": "query", "path": "/queries.json", "status": "500"},
        err, "counter", t=t,
    )


class TestBurnRate:
    def test_availability_burn_math(self):
        db = TSDB()
        # 100 requests in-window, 2 errors → fraction 0.02, budget 0.01
        _feed_availability(db, T0, 0, 0)
        _feed_availability(db, T0 + 10, 98, 2)
        engine = SLOEngine(db, [_avail_spec()], registry=MetricsRegistry())
        burn, samples = engine.burn_rate(
            _avail_spec(), window_s=10, now=T0 + 10
        )
        assert samples == 100
        assert burn == pytest.approx(2.0)

    def test_zero_traffic_window_returns_none_and_holds_state(self):
        db = TSDB()
        spec = _avail_spec(min_samples=1)
        engine = SLOEngine(db, [spec], registry=MetricsRegistry())
        # empty TSDB: no divide-by-zero, burn is None, state stays put
        burn, samples = engine.burn_rate(spec, 10, now=T0)
        assert burn is None and samples == 0
        engine.evaluate_once(now=T0)
        st = engine.status("avail")
        assert st.state == "inactive"
        # drive to firing, then cut traffic entirely: still firing
        _feed_availability(db, T0 + 1, 0, 0)
        _feed_availability(db, T0 + 5, 0, 50)
        engine.evaluate_once(now=T0 + 6)
        engine.evaluate_once(now=T0 + 7)
        assert engine.status("avail").state == "firing"
        engine.evaluate_once(now=T0 + 1000)  # every window empty now
        assert engine.status("avail").state == "firing"  # held, no flap

    def test_min_samples_guards_thin_traffic(self):
        db = TSDB()
        spec = _avail_spec(min_samples=10)
        engine = SLOEngine(db, [spec], registry=MetricsRegistry())
        _feed_availability(db, T0, 0, 0)
        _feed_availability(db, T0 + 5, 1, 2)  # 3 requests, 2 errors
        engine.evaluate_once(now=T0 + 5)
        assert engine.status("avail").state == "inactive"

    def test_pending_then_firing_then_resolved(self):
        db = TSDB()
        spec = _avail_spec()
        engine = SLOEngine(db, [spec], registry=MetricsRegistry())
        _feed_availability(db, T0, 0, 0)
        _feed_availability(db, T0 + 2, 50, 50)
        engine.evaluate_once(now=T0 + 3)
        assert engine.status("avail").state == "pending"
        engine.evaluate_once(now=T0 + 4)
        assert engine.status("avail").state == "firing"
        # errors age out of both windows; healthy traffic resumes
        _feed_availability(db, T0 + 100, 1000, 50)
        engine.evaluate_once(now=T0 + 105)
        assert engine.status("avail").state == "resolved"
        # a fresh breach re-enters through pending, not straight to firing
        _feed_availability(db, T0 + 110, 1000, 500)
        engine.evaluate_once(now=T0 + 111)
        assert engine.status("avail").state == "pending"

    def test_pending_clears_when_breach_stops(self):
        db = TSDB()
        spec = _avail_spec(for_s=60.0)  # long promotion delay
        engine = SLOEngine(db, [spec], registry=MetricsRegistry())
        _feed_availability(db, T0, 0, 0)
        _feed_availability(db, T0 + 2, 0, 20)
        engine.evaluate_once(now=T0 + 3)
        assert engine.status("avail").state == "pending"
        _feed_availability(db, T0 + 50, 5000, 20)
        engine.evaluate_once(now=T0 + 55)
        assert engine.status("avail").state == "inactive"

    def test_resolve_hysteresis(self):
        db = TSDB()
        spec = _avail_spec(resolve_s=30.0, fast_window_s=5.0,
                           window_s=10.0)
        engine = SLOEngine(db, [spec], registry=MetricsRegistry())
        _feed_availability(db, T0, 0, 0)
        _feed_availability(db, T0 + 2, 0, 50)
        engine.evaluate_once(now=T0 + 3)
        engine.evaluate_once(now=T0 + 4)
        assert engine.status("avail").state == "firing"
        # clean window, but the clear streak is shorter than resolve_s
        _feed_availability(db, T0 + 20, 500, 50)
        engine.evaluate_once(now=T0 + 25)
        assert engine.status("avail").state == "firing"
        # a breach mid-streak resets the hysteresis clock
        _feed_availability(db, T0 + 30, 500, 550)
        engine.evaluate_once(now=T0 + 32)
        assert engine.status("avail").state == "firing"
        _feed_availability(db, T0 + 60, 2000, 550)
        engine.evaluate_once(now=T0 + 65)   # clear #1 (streak starts)
        _feed_availability(db, T0 + 78, 3000, 550)
        engine.evaluate_once(now=T0 + 80)   # 15 s clear < 30 s
        assert engine.status("avail").state == "firing"
        _feed_availability(db, T0 + 94, 4000, 550)
        engine.evaluate_once(now=T0 + 96)   # 31 s clear ≥ 30 s
        assert engine.status("avail").state == "resolved"

    def test_latency_slo_reads_sampled_buckets(self):
        reg = MetricsRegistry()
        h = reg.histogram(
            "http_request_seconds", "", ("server", "path"),
            buckets=(0.1, 0.25, 1.0),
        )
        h.observe(0.05, server="query", path="/queries.json")
        db = TSDB()
        sample_families(db, reg.families(), now=T0)  # baseline tick
        for _ in range(89):
            h.observe(0.05, server="query", path="/queries.json")
        sample_families(db, reg.families(), now=T0 + 5)
        for _ in range(10):
            h.observe(0.9, server="query", path="/queries.json")
        sample_families(db, reg.families(), now=T0 + 10)
        spec = SLOSpec(
            name="lat", kind="latency", objective=0.95,
            threshold_ms=250.0, fast_window_s=20.0, window_s=40.0,
            burn_threshold=1.0,
        )
        engine = SLOEngine(db, [spec], registry=MetricsRegistry())
        # the first sample (count=1) is the baseline: 99 observed
        # requests in-window, 10 of them slower than 250 ms →
        # bad fraction 10/99, budget 0.05 → burn ≈ 2.02
        burn, samples = engine.burn_rate(spec, 20, now=T0 + 10)
        assert samples == 99
        assert burn == pytest.approx((10 / 99) / 0.05, rel=1e-6)

    def test_up_slo_fires_on_dead_target(self):
        db = TSDB()
        spec = SLOSpec(
            name="fleet-up", kind="up", instance="query",
            objective=0.9, fast_window_s=10.0, window_s=20.0,
            burn_threshold=1.0,
        )
        engine = SLOEngine(db, [spec], registry=MetricsRegistry())
        for i in range(5):
            db.add("up", {"instance": "query"}, 1.0, t=T0 + i)
        engine.evaluate_once(now=T0 + 5)
        assert engine.status("fleet-up").state == "inactive"
        for i in range(5, 10):
            db.add("up", {"instance": "query"}, 0.0, t=T0 + i)
        engine.evaluate_once(now=T0 + 10)
        engine.evaluate_once(now=T0 + 11)
        assert engine.status("fleet-up").state == "firing"

    def test_firing_gauge_exported(self):
        db = TSDB()
        reg = MetricsRegistry()
        spec = _avail_spec()
        engine = SLOEngine(db, [spec], registry=reg)
        _feed_availability(db, T0, 0, 0)
        _feed_availability(db, T0 + 2, 0, 50)
        engine.evaluate_once(now=T0 + 3)
        engine.evaluate_once(now=T0 + 4)
        assert reg.gauge(
            "alerts_firing", labelnames=("slo",)
        ).value(slo="avail") == 1.0

    def test_spec_validation_and_env_loading(self, tmp_path):
        with pytest.raises(ValueError):
            SLOSpec(name="x", objective=1.5)
        with pytest.raises(ValueError):
            SLOSpec(name="x", kind="nope")
        with pytest.raises(ValueError):
            SLOSpec(name="x", fast_window_s=100, window_s=10)
        with pytest.raises(ValueError):
            SLOSpec(name="x", kind="up")  # needs instance
        assert load_slos("") == []
        assert load_slos("{not json") == []  # warn, never raise
        assert load_slos('[{"name": "a", "bogus": 1}]') == []
        specs = load_slos(
            '[{"name": "a", "objective": 0.999, "kind": "availability"}]'
        )
        assert specs[0].budget == pytest.approx(0.001)
        p = tmp_path / "slos.json"
        p.write_text('[{"name": "f", "objective": 0.9}]')
        assert load_slos(f"@{p}")[0].name == "f"

    def test_engine_thread_joins(self):
        engine = SLOEngine(
            TSDB(), [_avail_spec()], interval_s=0.05,
            registry=MetricsRegistry(),
        )
        engine.start()
        engine.stop()
        assert not any(
            t.name == "slo-engine" for t in threading.enumerate()
        )


# ---------------------------------------------------------------------------
# the process-global Monitor (attach/detach hygiene)
# ---------------------------------------------------------------------------


MONITOR_THREADS = (
    "tsdb-sampler", "slo-engine", "fleet-scraper", "tsdb-snapshot",
)


def _monitor_threads():
    return [
        t.name for t in threading.enumerate()
        if t.name in MONITOR_THREADS and t.is_alive()
    ]


class TestMonitor:
    def test_attach_refcount_joins_on_last_detach(self):
        monitor = Monitor()
        monitor.sampler_interval_s = 0.05
        monitor.set_slos([_avail_spec()])
        t1 = monitor.attach("a", MetricsRegistry())
        t2 = monitor.attach("b", MetricsRegistry())
        assert "tsdb-sampler" in _monitor_threads()
        assert "slo-engine" in _monitor_threads()
        monitor.detach(t1)
        assert "tsdb-sampler" in _monitor_threads()
        monitor.detach(t2)
        assert _monitor_threads() == []
        monitor.detach(t2)  # double detach is a no-op

    def test_disabled_plane_attaches_nothing(self, monkeypatch):
        monkeypatch.setenv("PIO_TSDB", "0")
        monitor = Monitor()
        assert monitor.attach("a", MetricsRegistry()) is None
        assert _monitor_threads() == []
        payload = monitor.alerts_payload()
        assert payload["enabled"] is False
        assert monitor.tsdb_payload({})["enabled"] is False

    def test_server_stop_leaves_no_monitor_threads(self, fresh_storage):
        from predictionio_tpu.data.api.server import (
            EventServer,
            EventServerConfig,
        )
        from predictionio_tpu.obs.monitor import get_monitor

        before = get_monitor().attached_count
        srv = EventServer(
            fresh_storage,
            EventServerConfig(ip="127.0.0.1", port=0, wal_dir=None),
        )
        srv.start()
        assert get_monitor().attached_count == before + 1
        srv.stop()
        assert get_monitor().attached_count == before
        if before == 0:
            assert _monitor_threads() == []

    def test_same_named_families_across_servers_all_sampled(self):
        # two servers in one process each own an `http_requests_total`
        # family (disjoint server= children): BOTH must reach the TSDB
        # — dropping the later-attached server's family would blind its
        # SLOs — while exact-duplicate unlabeled gauges (the shared
        # jax/devprof callbacks) write once per tick, first wins
        monitor = Monitor()
        r1, r2 = MetricsRegistry(), MetricsRegistry()
        r1.counter("http_requests_total", "", ("server",)).inc(
            1, server="query"
        )
        r2.counter("http_requests_total", "", ("server",)).inc(
            2, server="storage"
        )
        r1.gauge_callback("devprof_mfu", "", lambda: 1.0)
        r2.gauge_callback("devprof_mfu", "", lambda: 2.0)
        monitor._attached = [(1, "query", r1), (2, "storage", r2)]
        sample_families(monitor.tsdb, monitor._families(), now=T0)
        db = monitor.tsdb
        assert db.latest("http_requests_total", {"server": "query"}) == 1
        assert db.latest("http_requests_total", {"server": "storage"}) == 2
        (mfu,) = db.matching("devprof_mfu")
        assert db.points(mfu) == [(T0, 1.0)]  # one point, first wins

    def test_tsdb_payload_queries(self):
        monitor = Monitor()
        db = monitor.tsdb
        now = time.time()  # the payload API anchors windows at wall now
        for i in range(5):
            db.add("c", {"k": "a"}, float(i), "counter", t=now - 5 + i)
        listing = monitor.tsdb_payload({})
        assert listing["series_count"] == 1
        pts = monitor.tsdb_payload({"name": "c", "labels": "k:a"})
        assert len(pts["series"][0]["points"]) == 5
        agg = monitor.tsdb_payload(
            {"name": "c", "agg": "increase", "window_s": "60"}
        )
        assert agg["value"] == 4.0


class TestDashboardPanels:
    def test_alerts_and_fleet_panels_render(self, fresh_storage):
        import urllib.request

        from predictionio_tpu.obs.monitor import get_monitor
        from predictionio_tpu.tools.dashboard import Dashboard

        monitor = get_monitor()
        monitor.set_slos([_avail_spec(name="panel-slo")])
        dash = Dashboard(
            fresh_storage, ip="127.0.0.1", port=0,
            monitor_targets="deadpeer=http://127.0.0.1:1",
            scrape_interval_s=60,
        )
        port = dash.start()
        try:
            dash._scraper.scrape_once()
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/", timeout=10
            ) as r:
                body = r.read().decode()
            assert "Alerts" in body and "panel-slo" in body
            assert "Fleet" in body and "deadpeer" in body
            assert "DOWN" in body  # the dead target is visibly down
        finally:
            dash.stop()
            monitor.set_slos([])
        # sampler + SLO engine + fleet scraper all joined with the server
        assert _monitor_threads() == []


# ---------------------------------------------------------------------------
# trace capture (ISSUE 8 satellite)
# ---------------------------------------------------------------------------


class TestTraceCapture:
    def test_capture_forces_retention_past_sampling(self):
        from predictionio_tpu.obs.spans import Span, SpanRecorder

        rec = SpanRecorder(max_traces=32, slow_ms=1e9, sample_rate=0.0)
        cap = rec.arm_capture(2)
        # two "batches" with one trace each, one uncaptured trace after
        assert rec.consume_capture() == cap
        rec.force_keep("t1", cap)
        assert rec.consume_capture() == cap
        rec.force_keep("t2", cap)
        assert rec.consume_capture() is None  # credits spent
        for tid in ("t1", "t2", "t3"):
            rec.record(
                Span(trace_id=tid, span_id=tid + "s", name="server.request",
                     start=time.time(), duration=0.001),
                finalize=True,
            )
        # sample_rate 0 would drop everything; capture kept t1/t2 only
        assert rec.get_trace("t1") and rec.get_trace("t2")
        assert not rec.get_trace("t3")
        status = rec.capture_status(cap)
        assert status["done"] is True
        assert sorted(status["capture"]["trace_ids"]) == ["t1", "t2"]
        assert len(status["traces"]) == 2
        assert rec.capture_status("nope") is None

    def test_force_keep_on_already_retained_trace(self):
        from predictionio_tpu.obs.spans import Span, SpanRecorder

        rec = SpanRecorder(max_traces=32, slow_ms=1e9, sample_rate=1.0)
        rec.record(
            Span(trace_id="t", span_id="s", name="server.request",
                 start=time.time(), duration=0.001),
            finalize=True,
        )
        cap = rec.arm_capture(1)
        rec.force_keep("t", cap)
        assert rec.capture_status(cap)["capture"]["trace_ids"] == ["t"]


# ---------------------------------------------------------------------------
# devprof loop-FLOPs calibration (ISSUE 8 satellite)
# ---------------------------------------------------------------------------


class _FakeLowered:
    def __init__(self, flops, nbytes):
        self._flops = flops
        self._bytes = nbytes

    def cost_analysis(self):
        return {"flops": self._flops, "bytes accessed": self._bytes}


class _FakeLoopFn:
    """Mimics a jit'd train loop: XLA counts the body once (cost is
    base + per_iter regardless of `iterations`) — unless lowered with
    an explicit iteration count, which this fake honors the way the
    real 1-vs-2 lowering diff expects."""

    def __init__(self, base=1000.0, per_iter=100.0):
        self.base = base
        self.per_iter = per_iter

    def __call__(self, x, iterations=1):
        return x

    def lower(self, x, iterations=1):
        return _FakeLowered(
            self.base + self.per_iter * iterations,
            10.0 + 1.0 * iterations,
        )


class TestDevprofCalibration:
    def test_one_vs_two_iteration_lowering(self):
        from predictionio_tpu.obs.devprof import (
            DeviceProfiler,
            _Instrumented,
            _SigAnalysis,
        )

        prof = DeviceProfiler()
        fn = _FakeLoopFn()
        wrapper = _Instrumented("fake.loop", fn, scale_by="iterations")
        res = _SigAnalysis()
        res.flops, res.bytes_accessed = 1100.0, 11.0  # the n=10 lowering
        res.cost_ok = True
        prof._calibrate_loop(
            wrapper, fn.lower, (0,), {"iterations": 10}, res
        )
        assert res.calibrated
        # cost(1)=1100, cost(2)=1200 → per_iter 100 → total(10)=2000
        assert res.flops == pytest.approx(2000.0)
        assert res.flops_body == pytest.approx(1100.0)
        # the kwarg-trusting estimate (1100 * 10 = 11000) would have
        # over-counted the loop-invariant base 10×

    def test_calibration_falls_back_on_lowering_failure(self):
        from predictionio_tpu.obs.devprof import (
            DeviceProfiler,
            _Instrumented,
            _SigAnalysis,
        )

        def bad_lower(*a, **k):
            raise RuntimeError("no lowering for you")

        wrapper = _Instrumented(
            "fake.loop2", lambda x, iterations=1: x, scale_by="iterations"
        )
        res = _SigAnalysis()
        res.flops, res.cost_ok = 500.0, True
        DeviceProfiler._calibrate_loop(
            wrapper, bad_lower, (0,), {"iterations": 4}, res
        )
        assert not res.calibrated  # caller keeps kwarg scaling
        assert res.flops == 500.0

    def test_flat_cost_falls_back_to_kwarg_scaling(self):
        # the real-XLA while-loop case: cost analysis counts the body
        # once, so the 1-vs-2 lowering diff is zero — calibration must
        # decline and leave the PR-3 kwarg scaling in charge
        from predictionio_tpu.obs.devprof import (
            DeviceProfiler,
            _Instrumented,
            _SigAnalysis,
        )

        def flat_lower(x, iterations=1):
            return _FakeLowered(1100.0, 10.0)  # trip-count blind

        wrapper = _Instrumented(
            "fake.flat", lambda x, iterations=1: x, scale_by="iterations"
        )
        res = _SigAnalysis()
        res.flops, res.cost_ok = 1100.0, True
        DeviceProfiler._calibrate_loop(
            wrapper, flat_lower, (0,), {"iterations": 10}, res
        )
        assert not res.calibrated
        assert res.flops == 1100.0  # caller multiplies by n, as before

    def test_report_carries_calibration_fields(self):
        from predictionio_tpu.obs import devprof

        prof = devprof.DeviceProfiler()
        fn = _FakeLoopFn()
        wrapper = devprof._Instrumented(
            "fake.loop3", fn, scale_by="iterations"
        )
        prof.call(wrapper, (1,), {"iterations": 10})
        row = prof.executable("fake.loop3")
        assert row["flops_scaled_by"] == "iterations"
        assert row["flops_calibrated"] is True
        assert row["flops_total"] == pytest.approx(2000.0)
        # the PR-3 kwarg-trusting estimate would have claimed
        # cost(n) * n = 2000 * 10 — kept in the report for comparison
        assert row["flops_per_call_kwarg_scaled"] == pytest.approx(20000.0)


# ---------------------------------------------------------------------------
# HBM-byte-bounded tenant model cache (ISSUE 8 satellite)
# ---------------------------------------------------------------------------


class _Tenant:
    def __init__(self, tid):
        self.id = tid
        self.engine_id = "e"
        self.engine_version = "0"
        self.engine_variant = "e"


class _Runtime:
    def __init__(self, mb):
        self.models = [np.zeros(int(mb * 1024 * 1024 // 8))]


class TestHbmCache:
    def _cache(self, hbm_mb, sizes_mb, transient_mb=0.0):
        from predictionio_tpu.tenancy.cache import ModelCache

        cache = ModelCache(
            storage=None,
            capacity=100,  # count bound out of the way: bytes decide
            build=lambda inst: _Runtime(sizes_mb[inst]),
            hbm_bytes=hbm_mb * 1024 * 1024,
            transient=lambda: transient_mb * 1024 * 1024,
        )
        cache.resolve_version = lambda tenant: (f"v-{tenant.id}", tenant.id)
        return cache

    def test_evicts_by_cumulative_bytes_not_count(self):
        sizes = {"a": 4, "b": 4, "c": 4}
        cache = self._cache(10, sizes)
        for tid in ("a", "b", "c"):
            cache.release(cache.acquire(_Tenant(tid)))
        # 12 MB resident > 10 MB budget → LRU ("a") evicted; two stay
        assert cache.evictions == 1
        assert sorted(cache.stats()["entries"]) == ["b", "c"]
        assert cache.resident_bytes() <= 10 * 1024 * 1024

    def test_one_oversized_model_still_serves(self):
        cache = self._cache(1, {"big": 8})
        entry = cache.acquire(_Tenant("big"))
        cache.release(entry)
        # soft-over-budget: the only entry is never evicted
        assert cache.stats()["resident"] == 1

    def test_inflight_and_pinned_survive_byte_pressure(self):
        sizes = {"a": 6, "b": 6, "c": 6}
        cache = self._cache(10, sizes)
        held = cache.acquire(_Tenant("a"))  # refs > 0
        cache.release(cache.acquire(_Tenant("b")))
        cache.pin("b")
        cache.release(cache.acquire(_Tenant("c")))
        stats = cache.stats()
        assert "a" in stats["entries"]  # in-flight: immune
        assert "b" in stats["entries"]  # pinned: immune
        cache.release(held)

    def test_count_bound_still_rules_without_hbm_budget(self):
        from predictionio_tpu.tenancy.cache import ModelCache

        cache = ModelCache(
            storage=None, capacity=2,
            build=lambda inst: _Runtime(1),
        )
        cache.resolve_version = lambda tenant: (f"v-{tenant.id}", tenant.id)
        for tid in ("a", "b", "c"):
            cache.release(cache.acquire(_Tenant(tid)))
        assert cache.stats()["resident"] == 2
        assert cache.evictions == 1

    def test_transient_reserved_once_not_per_entry(self):
        # budget 16, three 4 MB models + a 3 MB dispatch working set:
        # 12 + 3 fits; folding the transient into each entry (4+3 each
        # = 21) would wrongly evict. A 5 MB transient tips it over.
        sizes = {"a": 4, "b": 4, "c": 4}
        cache = self._cache(16, sizes, transient_mb=3)
        for tid in ("a", "b", "c"):
            cache.release(cache.acquire(_Tenant(tid)))
        assert cache.evictions == 0
        cache2 = self._cache(16, sizes, transient_mb=5)
        for tid in ("a", "b", "c"):
            cache2.release(cache2.acquire(_Tenant(tid)))
        assert cache2.evictions == 1

    def test_estimate_counts_model_array_bytes(self):
        from predictionio_tpu.tenancy.cache import (
            estimate_runtime_device_bytes,
        )

        rt = _Runtime(2)
        nbytes = estimate_runtime_device_bytes(rt)
        # exactly the model arrays — the dispatch transient is the
        # cache's budget-level reservation, not part of the entry
        assert nbytes == rt.models[0].nbytes


# ---------------------------------------------------------------------------
# TSDB snapshot persistence (ISSUE 15 satellite)
# ---------------------------------------------------------------------------


class TestTsdbSnapshot:
    def test_round_trip(self, tmp_path):
        from predictionio_tpu.obs.monitor import (
            TSDB, load_snapshot, save_snapshot,
        )

        t = TSDB(capacity=10)
        for i in range(20):  # ring wraps: only the newest 10 persist
            t.add("up", {"instance": "r0"}, i % 2, "gauge", 1000.0 + i)
            t.add("reqs_total", {"p": "/q"}, i * 3, "counter", 1000.0 + i)
        path = str(tmp_path / "snap.json")
        assert save_snapshot(t, path) > 0
        t2 = TSDB(capacity=10)
        assert load_snapshot(t2, path) == 2
        assert t2.latest("up", {"instance": "r0"}) == t.latest(
            "up", {"instance": "r0"}
        )
        (series,) = t2.matching("reqs_total")
        assert len(series.points) == 10
        assert series.kind == "counter"

    def test_corrupt_snapshot_tolerated(self, tmp_path):
        from predictionio_tpu.obs.monitor import TSDB, load_snapshot

        path = tmp_path / "snap.json"
        path.write_bytes(b"{definitely not json")
        t = TSDB()
        assert load_snapshot(t, str(path)) == 0
        assert t.series_count() == 0
        # missing file is silent too
        assert load_snapshot(t, str(tmp_path / "nope.json")) == 0

    def test_bounded_file_size_drops_oldest_points(self, tmp_path):
        from predictionio_tpu.obs.monitor import (
            TSDB, load_snapshot, save_snapshot,
        )

        big = TSDB(capacity=720, max_series=10_000)
        for s in range(100):
            for i in range(720):
                big.add("m", {"s": str(s)}, float(i), "gauge", float(i))
        path = str(tmp_path / "snap.json")
        n = save_snapshot(big, path, max_bytes=50_000)
        assert n <= 50_000
        t2 = TSDB(capacity=720, max_series=10_000)
        assert load_snapshot(t2, path) == 100  # every series survives...
        (series,) = [
            s for s in t2.matching("m", {"s": "7"})
        ]
        # ...with the NEWEST points kept
        assert series.points[-1][1] == 719.0

    def test_monitor_persists_across_restart(self, tmp_path, monkeypatch):
        """The wiring: a Monitor with PIO_TSDB_SNAPSHOT set writes on
        last detach and a fresh Monitor (the restart) reloads the
        history — the gateway's up{instance}/burn windows survive."""
        snap = str(tmp_path / "monitor-snap.json")
        monkeypatch.setenv("PIO_TSDB_SNAPSHOT", snap)
        monitor = Monitor()
        monitor.sampler_interval_s = 0.05
        token = monitor.attach("a", MetricsRegistry())
        monitor.tsdb.add("up", {"instance": "r9"}, 1.0, "gauge")
        monitor.detach(token)  # joins + final snapshot
        assert _monitor_threads() == []
        import os

        assert os.path.exists(snap)
        reborn = Monitor()
        assert reborn.tsdb.latest("up", {"instance": "r9"}) == 1.0
