"""Universal fused serving (ISSUE 14): interpret-mode parity for the
fused `similar` and CCO `batch_score_topk` tails against the XLA
two-step, bit-packed vs row-list mask equivalence, bf16/int8 dtype
invariance, sharded serve_dtype staging + donated dirty-row publish,
device-count invariance, per-dtype devprof columns, and pickle
migration for the models that grew serve_dtype fields."""

import dataclasses
import pickle

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from predictionio_tpu.data.store.bimap import BiMap  # noqa: E402
from predictionio_tpu.models import als, cco  # noqa: E402
from predictionio_tpu.ops import recommend_pallas as rp  # noqa: E402
from predictionio_tpu.ops.topk import NEG_INF, masked_top_k  # noqa: E402


def _factors(rng, u=50, i=300, k=10):
    return als.ALSFactors(
        user_factors=rng.standard_normal((u, k)).astype(np.float32),
        item_factors=rng.standard_normal((i, k)).astype(np.float32),
        user_vocab=BiMap({f"u{n}": n for n in range(u)}),
        item_vocab=BiMap({f"i{n}": n for n in range(i)}),
    )


# ---------------------------------------------------------------------------
# fused similar: exact parity vs the XLA two-step, same score semantics
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", ["f32", "bf16", "int8"])
def test_similar_mode_parity(dtype):
    """A mode change never changes `similar` scores within a dtype —
    the fused kernel and the XLA fallback share the scaled-dot cosine
    semantics exactly (indices bit-equal incl. tie order)."""
    rng = np.random.RandomState(20)
    f = _factors(rng)
    sv_i = dataclasses.replace(
        als.stage_serving(f, serve_dtype=dtype), mode="interpret"
    )
    sv_x = dataclasses.replace(sv_i, mode=None)
    v1, i1 = als.similar_serving(sv_i, np.arange(8), 11)
    v0, i0 = als.similar_serving(sv_x, np.arange(8), 11)
    assert np.array_equal(i0, i1)
    np.testing.assert_allclose(v0, v1, rtol=1e-5)
    for r in range(8):  # exclude_self holds on both paths
        assert r not in i1[r]


def test_similar_f32_matches_legacy_similar_items():
    """The fused scaled-dot cosine ranks identically to the legacy
    normalize-then-dot `als.similar_items` (values to f32 rounding)."""
    rng = np.random.RandomState(21)
    f = _factors(rng)
    sv = dataclasses.replace(
        als.stage_serving(f, serve_dtype="f32"), mode="interpret"
    )
    lv, li = als.similar_items(f, np.arange(6), 9)
    nv, ni = als.similar_serving(sv, np.arange(6), 9)
    assert np.array_equal(li, ni)
    np.testing.assert_allclose(lv, nv, rtol=1e-4, atol=1e-5)


def test_similar_cross_tile_ties_and_fully_masked_and_k_eq_n():
    """The ISSUE-named edge cases on the similar verb: duplicated
    cosine scores straddling the 128-row tile boundary keep the
    lax.top_k tie order; a fully-masked row returns NEG_INF at the
    reference order; k == n_items drains the whole list."""
    rng = np.random.RandomState(22)
    base = rng.standard_normal((130, 6)).astype(np.float32)
    itf = np.concatenate([base, base])  # every cosine appears twice
    f = als.ALSFactors(
        np.zeros((0, 6), np.float32), itf, BiMap({}), BiMap({})
    )
    sv_i = dataclasses.replace(
        als.stage_serving(f, serve_dtype="f32"), mode="interpret"
    )
    sv_x = dataclasses.replace(sv_i, mode=None)
    # no exclude_self so the duplicate-row ties actually collide
    v1, i1 = als.similar_serving(sv_i, np.arange(4), 50, exclude_self=False)
    v0, i0 = als.similar_serving(sv_x, np.arange(4), 50, exclude_self=False)
    assert np.array_equal(i0, i1)
    # fully-masked row: everything excluded
    mask = np.zeros((2, 260), bool)
    mask[1, :] = True
    v1, i1 = als.similar_serving(
        sv_i, np.arange(2), 7, exclude_self=False, exclude_mask=mask
    )
    v0, i0 = als.similar_serving(
        sv_x, np.arange(2), 7, exclude_self=False, exclude_mask=mask
    )
    assert np.array_equal(i0, i1)
    assert np.all(v1[1] == NEG_INF)
    # k == n_items
    v1, i1 = als.similar_serving(sv_i, [3], 260, exclude_self=False)
    v0, i0 = als.similar_serving(sv_x, [3], 260, exclude_self=False)
    assert np.array_equal(i0, i1)


def test_packed_vs_rowlist_equivalence():
    """The same exclusion set expressed as bit-packed words and as a
    row list yields identical answers on BOTH kernel modes."""
    rng = np.random.RandomState(23)
    f = _factors(rng)
    ex = np.full((8, 8), -1, np.int32)
    for r in range(8):
        ex[r, :5] = rng.choice(300, 5, replace=False)
    mask = np.zeros((8, 300), bool)
    for r in range(8):
        mask[r, ex[r, :5]] = True
    for mode in ("interpret", None):
        sv = dataclasses.replace(
            als.stage_serving(f, serve_dtype="f32"), mode=mode
        )
        vm, im = als.recommend_serving(
            sv, np.arange(8), 10, exclude_mask=mask
        )
        vr, ir = als.recommend_serving(
            sv, np.arange(8), 10, exclude_rows=ex
        )
        assert np.array_equal(im, ir), mode
        np.testing.assert_allclose(vm, vr, rtol=0)
        assert not np.any(mask[np.arange(8)[:, None], im])


def test_packed_mask_is_one_32th_of_f32_bytes():
    """The acceptance arithmetic: packed words carry exactly 1/32 the
    bytes an f32 0/1 mask of the same padded width would."""
    i_p = rp.pad_items(300)
    mask = np.random.RandomState(0).rand(16, 300) < 0.5
    words = rp.pack_mask_np(mask, i_p)
    assert words.nbytes * 32 == 16 * i_p * 4
    # semantics identical through the traced unpack
    back = np.asarray(rp.unpack_mask_jnp(jnp.asarray(words), 300))
    assert np.array_equal(back, mask)


def test_bf16_serving_halves_factor_bytes_and_is_mode_invariant():
    rng = np.random.RandomState(24)
    f = _factors(rng)
    sv16 = als.stage_serving(f, serve_dtype="bf16")
    sv32 = als.stage_serving(f, serve_dtype="f32")
    assert sv16.items.nbytes * 2 == sv32.items.nbytes
    a = als.recommend_serving(
        dataclasses.replace(sv16, mode="interpret"), np.arange(6), 9
    )
    b = als.recommend_serving(
        dataclasses.replace(sv16, mode=None), np.arange(6), 9
    )
    assert np.array_equal(a[1], b[1])
    np.testing.assert_allclose(a[0], b[0], rtol=1e-5)


# ---------------------------------------------------------------------------
# CCO batch_score_topk fused tail
# ---------------------------------------------------------------------------


def _cco_tables(rng, I=500, T=20, js=(120, 80)):
    tables, hists = [], []
    for J in js:
        idx = rng.randint(-1, J, (I, T)).astype(np.int32)
        sc = np.abs(rng.standard_normal((I, T))).astype(np.float32)
        tables.append((idx, sc, J))
        hists.append(rng.randint(-1, J, (8, 16)).astype(np.int32))
    return tables, hists


@pytest.mark.parametrize("width", [32, 128])
def test_cco_fused_matches_xla_exactly(width):
    """Fused CCO tail == the XLA scatter+where+top_k tail bit-for-bit
    on indices/tie order, for both the row-list (narrow) and the
    host-packed (wide) exclusion forms."""
    rng = np.random.RandomState(25)
    tables, hists = _cco_tables(rng)
    ex = np.full((8, width), -1, np.int32)
    for b in range(8):
        ex[b, :12] = rng.choice(500, 12, replace=False)
    v0, i0 = cco.batch_score_topk(tables, hists, ex, 17, mode="off")
    v1, i1 = cco.batch_score_topk(tables, hists, ex, 17, mode="interpret")
    assert np.array_equal(i0, i1)
    np.testing.assert_allclose(v0, v1, rtol=1e-6)


def test_cco_fused_ties_and_k_edge():
    """Crafted equal LLR sums across the tile boundary + k == n_items:
    the fused tail keeps lax.top_k's lowest-index tie order."""
    rng = np.random.RandomState(26)
    I, J = 256, 40
    # every item row carries the SAME correlator set → global ties
    idx = np.tile(rng.randint(0, J, (1, 6)), (I, 1)).astype(np.int32)
    sc = np.tile(
        np.abs(rng.standard_normal((1, 6))), (I, 1)
    ).astype(np.float32)
    hist = rng.randint(-1, J, (4, 8)).astype(np.int32)
    ex = np.full((4, 8), -1, np.int32)
    v0, i0 = cco.batch_score_topk(
        [(idx, sc, J)], [hist], ex, I, mode="off"
    )
    v1, i1 = cco.batch_score_topk(
        [(idx, sc, J)], [hist], ex, I, mode="interpret"
    )
    assert np.array_equal(i0, i1)
    np.testing.assert_allclose(v0, v1, rtol=1e-6)


def test_cco_host_reference_agreement_fused():
    """The fused path still matches the host reference scorer the XLA
    path is tested against (score_history)."""
    rng = np.random.RandomState(27)
    tables, hists = _cco_tables(rng, I=200, js=(60,))
    ex = np.full((8, 16), -1, np.int32)
    vals, idx = cco.batch_score_topk(
        tables, hists, ex, 5, mode="interpret"
    )
    for b in range(3):
        hist = hists[0][b]
        ref = cco.score_history(
            tables[0][0], tables[0][1], hist[hist >= 0]
        )
        order = np.argsort(-ref, kind="stable")[:5]
        assert np.array_equal(idx[b], order)
        np.testing.assert_allclose(vals[b], ref[order], rtol=1e-5)


# ---------------------------------------------------------------------------
# sharded tier: serve_dtype staging + donated dirty-row publish
# ---------------------------------------------------------------------------


needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the forced multi-device mesh"
)


@needs_mesh
def test_sharded_int8_resident_bytes_about_a_third():
    """Acceptance: int8 staging ≈ 1/3 of f32 resident bytes per shard
    (int8 cells + f32 scale/inverse-norm vectors) at a serving-real
    rank."""
    from predictionio_tpu.fleet.runtime import ShardedRuntime

    rng = np.random.RandomState(30)
    uf = rng.standard_normal((256, 64)).astype(np.float32)
    itf = rng.standard_normal((1024, 64)).astype(np.float32)
    r8 = ShardedRuntime(uf, itf, serve_dtype="int8")
    r32 = ShardedRuntime(uf, itf, serve_dtype="f32")
    ratio = (
        r8.device_bytes()["per_shard"] / r32.device_bytes()["per_shard"]
    )
    assert 0.2 < ratio < 0.4, ratio
    assert r8.info()["serve_dtype"] == "int8"


@needs_mesh
@pytest.mark.parametrize("dtype", ["f32", "bf16", "int8"])
@pytest.mark.parametrize("mode", ["off", "interpret"])
def test_sharded_device_count_invariance(dtype, mode):
    """The same query yields the same answer regardless of shard count
    — for every dtype and both kernel modes, on all three verbs."""
    from predictionio_tpu.fleet.runtime import ShardedRuntime
    from predictionio_tpu.parallel.mesh import serving_mesh

    rng = np.random.RandomState(31)
    uf = rng.standard_normal((40, 8)).astype(np.float32)
    itf = rng.standard_normal((570, 8)).astype(np.float32)
    runtimes = [
        ShardedRuntime(
            uf, itf, serve_dtype=dtype, serve_mode=mode,
            mesh=serving_mesh(n),
        )
        for n in (2, 8)
    ]
    mask = rng.rand(5, 570) < 0.3
    outs = [r.recommend(np.arange(5), 9, exclude_mask=mask) for r in runtimes]
    assert np.array_equal(outs[0][1], outs[1][1])
    np.testing.assert_allclose(outs[0][0], outs[1][0], rtol=1e-5)
    sims = [r.similar_items(np.arange(4), 7) for r in runtimes]
    assert np.array_equal(sims[0][1], sims[1][1])
    vecs = rng.standard_normal((3, 8)).astype(np.float32)
    vs = [r.similar_vectors(vecs, 6) for r in runtimes]
    assert np.array_equal(vs[0][1], vs[1][1])


@needs_mesh
def test_sharded_int8_matches_single_device_int8():
    """Sharded int8 serving and the single-device int8 staged state
    share quantization semantics exactly (same scales, same int32
    accumulate) — indices bit-equal."""
    from predictionio_tpu.fleet.runtime import ShardedRuntime

    rng = np.random.RandomState(32)
    f = _factors(rng, u=40, i=570, k=8)
    srt = ShardedRuntime(
        f.user_factors, f.item_factors, serve_dtype="int8",
        serve_mode="off",
    )
    sv = dataclasses.replace(
        als.stage_serving(f, serve_dtype="int8"), mode=None
    )
    v0, i0 = als.recommend_serving(sv, np.arange(6), 10)
    v1, i1 = srt.recommend(np.arange(6), 10)
    assert np.array_equal(i0, i1)
    np.testing.assert_allclose(v0, v1, rtol=1e-5)


@needs_mesh
@pytest.mark.parametrize("dtype", ["f32", "int8"])
def test_sharded_publish_requantizes_only_dirty_rows(dtype, monkeypatch):
    """Acceptance regression: a fold-in publish into the sharded tier
    re-quantizes/ships ONLY the dirty rows — no full restage (any
    full-matrix staging call after init trips the tripwire), and the
    published rows serve immediately, with fresh cosine norms."""
    from predictionio_tpu.fleet import runtime as rt_mod
    from predictionio_tpu.parallel import mesh as mesh_mod

    rng = np.random.RandomState(33)
    uf = rng.standard_normal((40, 8)).astype(np.float32)
    itf = rng.standard_normal((570, 8)).astype(np.float32)
    srt = rt_mod.ShardedRuntime(uf, itf, serve_dtype=dtype)

    def tripwire(*a, **k):  # pragma: no cover - failure path
        raise AssertionError("full restage attempted after init")

    monkeypatch.setattr(rt_mod, "shard_rows", tripwire)
    monkeypatch.setattr(mesh_mod, "shard_rows", tripwire)
    quant_rows = []
    orig_q = rt_mod._devprof  # keep lint quiet about unused
    import predictionio_tpu.ops.recommend_pallas as rp_mod

    orig_quant = rp_mod.quantize_rows_np

    def spy_quant(arr):
        quant_rows.append(np.asarray(arr).shape[0])
        return orig_quant(arr)

    monkeypatch.setattr(rp_mod, "quantize_rows_np", spy_quant)
    before_v, before_i = srt.recommend([2], 5)
    boost = np.full((2, 8), 9.0, np.float32)
    srt.update_item_rows(np.array([7, 8]), boost)
    srt.update_user_rows(
        np.array([2]), np.full((1, 8), 1.0, np.float32)
    )
    if dtype == "int8":
        # only the dirty rows were quantized: 2 item rows + 1 user row
        assert quant_rows == [2, 1], quant_rows
    _, idx = srt.recommend([2], 2)
    assert set(np.asarray(idx[0])) == {7, 8}
    # fresh inverse norms under similar: the identical boosted rows
    # are each other's nearest neighbors
    s = srt.similar_items(np.array([7]), 1)
    assert s[1][0][0] == 8


@needs_mesh
def test_sharded_publish_zero_drop_under_concurrent_readers():
    """Readers hammering recommend() while publishes land must never
    see an error or a malformed answer — the donated path drains
    leases first and falls back to COW on timeout."""
    import threading

    from predictionio_tpu.fleet.runtime import ShardedRuntime

    rng = np.random.RandomState(34)
    uf = rng.standard_normal((40, 8)).astype(np.float32)
    itf = rng.standard_normal((570, 8)).astype(np.float32)
    srt = ShardedRuntime(uf, itf, serve_dtype="int8")
    errors = []
    stop = threading.Event()

    def reader():
        while not stop.is_set():
            try:
                v, ix = srt.recommend(np.arange(4), 5)
                assert ix.shape == (4, 5)
            except Exception as e:  # pragma: no cover - failure path
                errors.append(e)
                return

    threads = [
        threading.Thread(target=reader, daemon=True) for _ in range(3)
    ]
    for t in threads:
        t.start()
    for i in range(10):
        srt.update_user_rows(
            np.array([i]),
            rng.standard_normal((1, 8)).astype(np.float32),
        )
    stop.set()
    for t in threads:
        t.join(timeout=10)
    assert not errors


@needs_mesh
def test_foldin_clone_carries_sharded_runtime():
    """online fold-in → _clone_model publishes the tick's dirty rows
    into the RESIDENT sharded runtime (no restage), and drops the
    carry when a changed side has no row attribution."""
    from predictionio_tpu.engines.recommendation.engine import ALSModel
    from predictionio_tpu.online.foldin import ALSFoldIn

    rng = np.random.RandomState(35)
    f = _factors(rng, u=40, i=570, k=8)
    model = ALSModel(f, serve_dtype="int8")
    model.params_shard = True
    srt = None
    # stage the sharded runtime through the model's own hook
    from predictionio_tpu.fleet.runtime import ShardedRuntime

    model._sharded_runtime = ShardedRuntime(
        f.user_factors, f.item_factors, serve_dtype="int8"
    )
    srt = model._sharded_runtime
    solved = rng.standard_normal((2, 8)).astype(np.float32)
    new_uf = f.user_factors.copy()
    new_uf[[1, 2]] = solved
    nf = dataclasses.replace(f, user_factors=new_uf)
    clone = ALSFoldIn._clone_model(
        model, nf, items_changed=False,
        dirty_users=([1, 2], solved),
    )
    assert clone._sharded_runtime is srt
    # the resident runtime serves the folded rows
    ref = ShardedRuntime(
        new_uf, f.item_factors, serve_dtype="int8"
    )
    a = srt.recommend([1], 5)
    b = ref.recommend([1], 5)
    assert np.array_equal(a[1], b[1])
    # a changed side without rows drops the carry
    clone2 = ALSFoldIn._clone_model(model, nf, items_changed=False)
    assert clone2._sharded_runtime is None


# ---------------------------------------------------------------------------
# engine wiring: itemsim fused cosine + similarproduct staged basket
# ---------------------------------------------------------------------------


def test_itemsim_staged_cosine_matches_legacy_host_path():
    from predictionio_tpu.engines.itemsim.engine import (
        ItemSimAlgorithm,
        ItemSimAlgorithmParams,
        ItemSimModel,
        Query,
    )
    from predictionio_tpu.models import ranking

    rng = np.random.RandomState(36)
    m = (rng.rand(30, 40) < 0.2).astype(np.float32)
    vocab = BiMap({f"i{j}": j for j in range(40)})
    model = ItemSimModel(
        sim_scores=np.zeros((0, 0), np.float32),
        sim_idx=np.zeros((0, 0), np.int64),
        item_vocab=vocab,
        top_n=10,
        item_vectors=np.ascontiguousarray(m.T),
    )
    algo = ItemSimAlgorithm(ItemSimAlgorithmParams(top_n=10))
    got = algo.predict(model, Query(items=["i1", "i3"], num=5))
    # legacy reference: normalize-then-dot + stable argsort
    normed = ranking.l2_normalize(model.item_vectors)
    known = [1, 3]
    scores = normed[known] @ normed.T
    scores[np.arange(2), known] = NEG_INF
    total = np.zeros(40, np.float32)
    idx = np.argsort(-scores, axis=1, kind="stable")[:, :10]
    vals = np.take_along_axis(scores, idx, axis=1)
    for r in range(2):
        ok = vals[r] > NEG_INF / 2
        np.add.at(total, idx[r][ok], vals[r][ok])
    total[known] = 0.0
    top = np.argsort(-total)[:5]
    want = [f"i{ix}" for ix in top if total[ix] > 0.0]
    assert [s.item for s in got.item_scores] == want


def test_itemsim_int8_staged_serving_ranks_sanely():
    from predictionio_tpu.engines.itemsim.engine import (
        ItemSimAlgorithm,
        ItemSimAlgorithmParams,
        ItemSimModel,
        Query,
    )

    rng = np.random.RandomState(37)
    m = (rng.rand(30, 40) < 0.25).astype(np.float32)
    vocab = BiMap({f"i{j}": j for j in range(40)})
    model = ItemSimModel(
        sim_scores=np.zeros((0, 0), np.float32),
        sim_idx=np.zeros((0, 0), np.int64),
        item_vocab=vocab,
        top_n=10,
        item_vectors=np.ascontiguousarray(m.T),
        serve_dtype="int8",
    )
    algo = ItemSimAlgorithm(
        ItemSimAlgorithmParams(top_n=10, serve_dtype="int8")
    )
    got = algo.predict(model, Query(items=["i1"], num=5))
    assert got.item_scores
    assert all(s.item != "i1" for s in got.item_scores)
    assert model.item_serving().dtype == "int8"


def test_similarproduct_staged_basket_matches_host_scores():
    """serve_dtype='f32' forced through the staged verb must reproduce
    the host path's SCORES (the qnorm-multiplied contract), not just
    its ranking."""
    from predictionio_tpu.engines.similarproduct.engine import (
        ALSSimilarAlgorithm,
        ALSSimilarParams,
        Query,
        SimilarModel,
    )

    rng = np.random.RandomState(38)
    f = _factors(rng, u=20, i=60, k=8)
    host = SimilarModel(f, serve_dtype="f32")
    staged = SimilarModel(f, serve_dtype="f32")
    algo_host = ALSSimilarAlgorithm(ALSSimilarParams())
    algo_staged = ALSSimilarAlgorithm(ALSSimilarParams())
    q = Query(items=["i1", "i5"], num=7)
    ref = algo_host._predict(host, q)
    # force the staged route by pretending bf16 staging with f32 data:
    # serve_dtype f32 + CPU resolves the host path, so flip the knob
    algo_staged.params = ALSSimilarParams(serve_dtype="bf16")
    staged.serve_dtype = "f32"  # stage exact factors, fused route
    got = algo_staged._predict(staged, q)
    ref_map = {s.item: s.score for s in ref.item_scores}
    got_map = {s.item: s.score for s in got.item_scores}
    assert set(got_map) == set(ref_map)
    for k_, v in got_map.items():
        assert v == pytest.approx(ref_map[k_], rel=1e-4)


# ---------------------------------------------------------------------------
# pickle migration (models gaining serve_dtype fields)
# ---------------------------------------------------------------------------


def test_similarmodel_pickle_migration():
    from predictionio_tpu.engines.similarproduct.engine import SimilarModel

    rng = np.random.RandomState(40)
    f = _factors(rng, u=10, i=20, k=4)
    m = SimilarModel(f, serve_dtype="int8")
    m2 = pickle.loads(pickle.dumps(m))
    assert m2.serve_dtype == "int8"
    # a pre-ISSUE-14 pickle carried only {"factors": ...}
    legacy = SimilarModel.__new__(SimilarModel)
    legacy.__setstate__({"factors": f})
    assert legacy.serve_dtype == "f32"
    assert legacy.normed_item_factors().shape == (20, 4)


def test_itemsim_pickle_migration():
    from predictionio_tpu.engines.itemsim.engine import ItemSimModel

    vocab = BiMap({"a": 0})
    m = ItemSimModel(
        sim_scores=np.zeros((1, 1), np.float32),
        sim_idx=np.zeros((1, 1), np.int64),
        item_vocab=vocab,
        serve_dtype="bf16",
    )
    m2 = pickle.loads(pickle.dumps(m))
    assert m2.serve_dtype == "bf16"
    # pre-ISSUE-14 state without the field defaults to f32
    legacy = ItemSimModel.__new__(ItemSimModel)
    legacy.__setstate__({
        "sim_scores": np.zeros((1, 1), np.float32),
        "sim_idx": np.zeros((1, 1), np.int64),
        "item_vocab": vocab,
    })
    assert legacy.serve_dtype == "f32" and legacy.top_n == 50


# ---------------------------------------------------------------------------
# devprof: per-dtype columns for mixed-dtype executables
# ---------------------------------------------------------------------------


def test_devprof_mixed_dtype_executable_reports_both_columns(monkeypatch):
    from predictionio_tpu.obs import devprof

    monkeypatch.setenv("PIO_PEAK_FLOPS", "1e12")
    monkeypatch.setenv("PIO_PEAK_FLOPS_INT8", "4e12")
    monkeypatch.setenv("PIO_PEAK_FLOPS_F32", "5e11")
    prof = devprof.DeviceProfiler()
    monkeypatch.setattr(devprof, "_profiler", prof)

    calls = {"dt": "f32"}
    fn = jax.jit(lambda a, b: a @ b)
    wrapped = devprof.instrument(
        "test.mixed_mm", fn, dtype_of=lambda a, k: calls["dt"]
    )
    x32 = jnp.asarray(
        np.random.RandomState(0).standard_normal((64, 64)), jnp.float32
    )
    np.asarray(wrapped(x32, x32))
    calls["dt"] = "int8"
    x16 = jnp.asarray(
        np.random.RandomState(0).standard_normal((128, 128)),
        jnp.float32,
    )
    np.asarray(wrapped(x16, x16))
    rep = prof.executable("test.mixed_mm")
    assert rep is not None
    cols = rep.get("dtypes")
    assert cols is not None and set(cols) == {"f32", "int8"}
    assert cols["f32"]["peak_flops"] == 5e11
    assert cols["int8"]["peak_flops"] == 4e12
    assert cols["f32"]["invocations"] == 1
    assert cols["int8"]["invocations"] == 1
    # the legacy scalar fields still reflect the LATEST signature
    assert rep["dtype"] == "int8"


def test_serving_similar_reports_dtype():
    from predictionio_tpu.obs import devprof

    rng = np.random.RandomState(41)
    f = _factors(rng, u=16, i=200, k=8)
    sv = als.stage_serving(f, serve_dtype="int8")
    als.similar_serving(sv, np.arange(4), 5)
    rep = devprof.get_profiler().executable("als.similar_serving")
    assert rep is not None and rep.get("dtype") in ("int8", "f32", "bf16")


def test_xla_scores_batch_size_invariant():
    """The shadow-rollout agreement contract: a B=1 mirror and a B=n
    live batch of the SAME query must produce bit-identical scores on
    the XLA fallback (the transposed-contraction dot_general this PR
    briefly used rounded differently per batch size — regression)."""
    rng = np.random.RandomState(50)
    f = _factors(rng, u=16, i=40, k=8)
    for dt in ("f32", "bf16", "int8"):
        sv = dataclasses.replace(
            als.stage_serving(f, serve_dtype=dt), mode=None
        )
        single = als.recommend_serving(sv, [3], 7)
        batched = als.recommend_serving(sv, [0, 3, 5, 7], 7)
        assert np.array_equal(single[1][0], batched[1][1]), dt
        assert np.array_equal(single[0][0], batched[0][1]), dt
        s1 = als.similar_serving(sv, [3], 7)
        s4 = als.similar_serving(sv, [0, 3, 5, 7], 7)
        assert np.array_equal(s1[1][0], s4[1][1]), dt
        assert np.array_equal(s1[0][0], s4[0][1]), dt


@needs_mesh
def test_sharded_within_pad_growth_becomes_servable():
    """Within-pad item growth through the fold-in carry must raise the
    LIVE extent — without it the grown rows stay masked dead under the
    verbs' live-count gates while the single-device tier serves them
    (review regression)."""
    from predictionio_tpu.fleet.runtime import ShardedRuntime

    rng = np.random.RandomState(51)
    uf = rng.standard_normal((16, 8)).astype(np.float32)
    itf = rng.standard_normal((100, 8)).astype(np.float32)
    srt = ShardedRuntime(uf, itf, serve_dtype="int8")
    i_p = int(srt._state.itf.shape[0])
    assert i_p > 102  # pad headroom exists
    boost = np.full((2, 8), 9.0, np.float32)
    srt.update_item_rows(np.array([100, 101]), boost, n_items=102)
    assert srt.n_items == 102
    srt.update_user_rows(
        np.array([0]), np.full((1, 8), 1.0, np.float32), n_users=16
    )
    _, idx = srt.recommend([0], 2)
    assert set(np.asarray(idx[0])) == {100, 101}
