"""Driver entry contract: entry() jits single-chip; dryrun_multichip runs a
sharded training step on the virtual 8-device mesh."""

import sys
from pathlib import Path

import jax

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import __graft_entry__ as graft  # noqa: E402


def test_entry_compiles_and_runs():
    fn, args = graft.entry()
    vals, idx = jax.jit(fn)(*args)
    assert vals.shape == (8, 10)
    assert idx.shape == (8, 10)


def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)


def test_dryrun_multichip_1():
    graft.dryrun_multichip(1)


def test_dryrun_self_provisions_when_devices_insufficient():
    """The driver environment sees ONE real chip; dryrun_multichip must
    still succeed by spawning a virtual-CPU subprocess (VERDICT r1 #1)."""
    from predictionio_tpu.parallel.dryrun import run_dryrun_subprocess

    run_dryrun_subprocess(8)
