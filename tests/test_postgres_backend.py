"""Postgres backend wiring + live-server gate + daemon write stress.

- Registry resolves `type=postgres` and fails with a clear message when no
  driver/server is present (this image has neither — the live contract
  run is gated on PIO_TEST_POSTGRES_DSN, matching VERDICT r2 #3's
  "skippable when no server is reachable").
- The multi-process durability item that IS testable here: ≥4 OS
  processes hammering the storage daemon concurrently must lose no
  events (sqlite WAL behind one daemon process).
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from predictionio_tpu.data.storage.base import StorageError
from predictionio_tpu.data.storage.registry import (
    SourceConfig,
    Storage,
    StorageConfig,
)

REPO = Path(__file__).resolve().parent.parent

PG_DSN = os.environ.get("PIO_TEST_POSTGRES_DSN")


def _driver_available() -> bool:
    try:
        from predictionio_tpu.data.storage.postgres import _load_driver

        _load_driver()
        return True
    except StorageError:
        return False


def test_registry_resolves_postgres_type():
    cfg = StorageConfig(
        sources={"PG": SourceConfig("PG", "postgres", {"HOST": "127.0.0.1"})},
        repositories={"METADATA": "PG", "EVENTDATA": "PG", "MODELDATA": "PG"},
    )
    storage = Storage(cfg)
    if _driver_available():
        # driver present but (in CI) no server: a clear connection error
        with pytest.raises(StorageError, match="connect"):
            storage.get_meta_data_apps()
    else:
        with pytest.raises(StorageError, match="psycopg2 or pg8000"):
            storage.get_meta_data_apps()


@pytest.mark.skipif(
    not PG_DSN, reason="PIO_TEST_POSTGRES_DSN not set (no postgres server)"
)
def test_live_postgres_contract():
    """Full event-store round trip against a real server. The complete
    contract suite additionally runs against this backend through the
    sqlite-backed fake driver (tests/test_storage_contract.py)."""
    import datetime as dt

    from predictionio_tpu.data.event import Event
    from predictionio_tpu.data.storage.base import EventQuery
    from predictionio_tpu.data.storage.postgres import PostgresEventStore

    store = PostgresEventStore({"URL": PG_DSN})
    app = 990_001
    store.remove_app(app)
    store.init_app(app)
    try:
        t0 = dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)
        ids = store.insert_batch(
            [
                Event(event="buy", entity_type="user", entity_id=f"u{i}",
                      event_time=t0 + dt.timedelta(seconds=i))
                for i in range(100)
            ],
            app,
        )
        assert len(set(ids)) == 100
        got = list(store.find(EventQuery(app_id=app)))
        assert [e.entity_id for e in got] == [f"u{i}" for i in range(100)]
        assert store.delete(ids[0], app)
        assert store.get(ids[0], app) is None
    finally:
        store.remove_app(app)


def test_daemon_concurrent_writers_no_lost_events(tmp_path):
    """≥4 writer processes hammer the storage daemon; every event must
    land exactly once (VERDICT r2 #3: daemon hardening under concurrency;
    sqlite WAL mode is the backing store)."""
    sys.path.insert(0, str(REPO / "tests"))
    from test_remote_storage import _free_port, _wait_health

    port = _free_port()
    env = dict(os.environ)
    env.update(
        {
            "PYTHONPATH": str(REPO) + os.pathsep + env.get("PYTHONPATH", ""),
            "PIO_STORAGE_SOURCES_SQL_TYPE": "sqlite",
            "PIO_STORAGE_SOURCES_SQL_PATH": str(tmp_path / "stress.db"),
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQL",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQL",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SQL",
        }
    )
    daemon = subprocess.Popen(
        [
            sys.executable, "-m",
            "predictionio_tpu.data.api.storage_server",
            "--host", "127.0.0.1", "--port", str(port),
        ],
        env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    n_writers, n_events = 6, 400
    writer_code = f"""
import json, sys
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.remote import RemoteEventStore

wid = int(sys.argv[1])
store = RemoteEventStore({{"HOST": "127.0.0.1", "PORT": "{port}"}})
store.init_app(1)
ids = []
for j in range({n_events} // 8):
    batch = [
        Event(event="w", entity_type="writer", entity_id=f"w{{wid}}-{{j * 8 + k}}")
        for k in range(8)
    ]
    ids.extend(store.insert_batch(batch, 1))
print(json.dumps({{"wid": wid, "n": len(ids), "unique": len(set(ids))}}))
"""
    try:
        _wait_health(port)
        writers = [
            subprocess.Popen(
                [sys.executable, "-c", writer_code, str(w)],
                env=env, cwd=REPO,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            )
            for w in range(n_writers)
        ]
        for w in writers:
            out, err = w.communicate(timeout=120)
            assert w.returncode == 0, err
            stats = json.loads(out.strip().splitlines()[-1])
            assert stats["n"] == stats["unique"] == n_events

        # read everything back through a fresh client: exact multiset
        from predictionio_tpu.data.storage.base import EventQuery
        from predictionio_tpu.data.storage.remote import RemoteEventStore

        store = RemoteEventStore({"HOST": "127.0.0.1", "PORT": str(port)})
        got = [e.entity_id for e in store.find(EventQuery(app_id=1))]
        assert len(got) == n_writers * n_events
        assert len(set(got)) == n_writers * n_events
        expect = {
            f"w{w}-{i}" for w in range(n_writers) for i in range(n_events)
        }
        assert set(got) == expect
    finally:
        daemon.terminate()
        daemon.wait(timeout=10)
