"""Deterministic id-stamping fake engines for workflow tests.

The key test pattern of the reference (core/src/test/scala/io/prediction/
controller/SampleEngine.scala, 472 LoC): every DASE stage stamps its params
id into the objects flowing through, so tests assert the exact data path
without any real ML.
"""

from __future__ import annotations

import pickle
from dataclasses import dataclass, field

from predictionio_tpu.controller import (
    Algorithm,
    DataSource,
    EngineFactory,
    Engine,
    FirstServing,
    LocalFileSystemPersistentModel,
    Preparator,
    SanityCheck,
    Serving,
)


# -- data carriers ----------------------------------------------------------


@dataclass
class TrainingData(SanityCheck):
    id: int
    error: bool = False

    def sanity_check(self):
        if self.error:
            raise ValueError(f"training data {self.id} is dirty")


@dataclass
class PreparedData:
    td_id: int
    p_id: int


@dataclass
class EvalInfo:
    id: int


@dataclass
class Query:
    q: int
    supplemented: bool = False


@dataclass
class Actual:
    q: int


@dataclass
class Prediction:
    q: int
    algo_id: int
    td_id: int
    p_id: int
    supplemented: bool = False


# -- params -----------------------------------------------------------------


@dataclass
class DSP:
    id: int = 0
    error: bool = False


@dataclass
class PP:
    id: int = 0


@dataclass
class AP:
    id: int = 0


# -- stages -----------------------------------------------------------------


class DataSource0(DataSource):
    def __init__(self, params: DSP):
        self.params = params

    def read_training(self, ctx):
        return TrainingData(id=self.params.id, error=self.params.error)

    def read_eval(self, ctx):
        return [
            (
                TrainingData(id=self.params.id),
                EvalInfo(id=s),
                [(Query(q=10 * s + i), Actual(q=10 * s + i)) for i in range(3)],
            )
            for s in range(2)
        ]


class Preparator0(Preparator):
    def __init__(self, params: PP):
        self.params = params

    def prepare(self, ctx, td: TrainingData) -> PreparedData:
        return PreparedData(td_id=td.id, p_id=self.params.id)


@dataclass
class Model0:
    algo_id: int
    td_id: int
    p_id: int


class Algo0(Algorithm):
    def __init__(self, params: AP):
        self.params = params

    def train(self, ctx, pd: PreparedData) -> Model0:
        return Model0(algo_id=self.params.id, td_id=pd.td_id, p_id=pd.p_id)

    def predict(self, model: Model0, query: Query) -> Prediction:
        return Prediction(
            q=query.q,
            algo_id=model.algo_id,
            td_id=model.td_id,
            p_id=model.p_id,
            supplemented=query.supplemented,
        )


class Algo1(Algo0):
    """Same behavior, distinct class for multi-algo binding tests."""


class NoParamsAlgo(Algorithm):
    """Zero-arg constructor → Doer's no-params path."""

    def train(self, ctx, pd: PreparedData) -> Model0:
        return Model0(algo_id=-1, td_id=pd.td_id, p_id=pd.p_id)

    def predict(self, model, query):
        return Prediction(
            q=query.q, algo_id=-1, td_id=model.td_id, p_id=model.p_id
        )


@dataclass
class PersistentModel0(LocalFileSystemPersistentModel):
    """User-managed persistence path (PersistentModelManifest mode)."""

    algo_id: int = 0
    td_id: int = 0
    p_id: int = 0


class PersistentAlgo(Algorithm):
    def __init__(self, params: AP):
        self.params = params

    def train(self, ctx, pd: PreparedData) -> PersistentModel0:
        return PersistentModel0(
            algo_id=self.params.id, td_id=pd.td_id, p_id=pd.p_id
        )

    def predict(self, model, query):
        return Prediction(
            q=query.q, algo_id=model.algo_id, td_id=model.td_id, p_id=model.p_id
        )


class UnserializableModel:
    """Defeats pickle → RetrainOnDeploy path."""

    def __init__(self, algo_id, td_id, p_id):
        self.algo_id, self.td_id, self.p_id = algo_id, td_id, p_id
        self.closure = lambda: None  # not picklable

    def __reduce__(self):
        raise pickle.PicklingError("deliberately unserializable")


class UnserializableAlgo(Algorithm):
    def __init__(self, params: AP):
        self.params = params

    def train(self, ctx, pd: PreparedData):
        return UnserializableModel(self.params.id, pd.td_id, pd.p_id)

    def predict(self, model, query):
        return Prediction(
            q=query.q, algo_id=model.algo_id, td_id=model.td_id, p_id=model.p_id
        )


class SupplementServing(Serving):
    """Stamps supplement + serves first prediction."""

    def supplement(self, query: Query) -> Query:
        return Query(q=query.q, supplemented=True)

    def serve(self, query, predictions):
        return predictions[0]


class SumServing(Serving):
    """Combines multi-algo predictions: sums algo ids."""

    def serve(self, query, predictions):
        p = predictions[0]
        return Prediction(
            q=p.q,
            algo_id=sum(x.algo_id for x in predictions),
            td_id=p.td_id,
            p_id=p.p_id,
            supplemented=p.supplemented,
        )


# -- engines ----------------------------------------------------------------


@dataclass
class SlowDSP:
    id: int = 0
    sleep_s: float = 30.0


class SlowDataSource(DataSource):
    """Sleeps through read_training — scheduler chaos tests kill the
    train subprocess while it sits here."""

    def __init__(self, params: SlowDSP):
        self.params = params

    def read_training(self, ctx):
        import time

        time.sleep(self.params.sleep_s)
        return TrainingData(id=self.params.id)


class Engine0Factory(EngineFactory):
    def apply(self):
        return Engine(
            DataSource0,
            Preparator0,
            {"algo0": Algo0, "algo1": Algo1, "noparams": NoParamsAlgo},
            {"": FirstServing, "sum": SumServing, "supp": SupplementServing},
        )


class PersistentEngineFactory(EngineFactory):
    def apply(self):
        return Engine(DataSource0, Preparator0, PersistentAlgo, FirstServing)


class SlowEngineFactory(EngineFactory):
    def apply(self):
        return Engine(SlowDataSource, Preparator0, Algo0, FirstServing)


class UnserializableEngineFactory(EngineFactory):
    def apply(self):
        return Engine(DataSource0, Preparator0, UnserializableAlgo, FirstServing)
