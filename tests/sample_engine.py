"""Deterministic id-stamping fake engines for workflow tests.

The key test pattern of the reference (core/src/test/scala/io/prediction/
controller/SampleEngine.scala, 472 LoC): every DASE stage stamps its params
id into the objects flowing through, so tests assert the exact data path
without any real ML.
"""

from __future__ import annotations

import pickle
from dataclasses import dataclass, field

from predictionio_tpu.controller import (
    Algorithm,
    DataSource,
    EngineFactory,
    Engine,
    FirstServing,
    LocalFileSystemPersistentModel,
    Preparator,
    SanityCheck,
    Serving,
)


# -- data carriers ----------------------------------------------------------


@dataclass
class TrainingData(SanityCheck):
    id: int
    error: bool = False

    def sanity_check(self):
        if self.error:
            raise ValueError(f"training data {self.id} is dirty")


@dataclass
class PreparedData:
    td_id: int
    p_id: int


@dataclass
class EvalInfo:
    id: int


@dataclass
class Query:
    q: int
    supplemented: bool = False


@dataclass
class Actual:
    q: int


@dataclass
class Prediction:
    q: int
    algo_id: int
    td_id: int
    p_id: int
    supplemented: bool = False


# -- params -----------------------------------------------------------------


@dataclass
class DSP:
    id: int = 0
    error: bool = False


@dataclass
class PP:
    id: int = 0


@dataclass
class AP:
    id: int = 0


# -- stages -----------------------------------------------------------------


class DataSource0(DataSource):
    def __init__(self, params: DSP):
        self.params = params

    def read_training(self, ctx):
        return TrainingData(id=self.params.id, error=self.params.error)

    def read_eval(self, ctx):
        return [
            (
                TrainingData(id=self.params.id),
                EvalInfo(id=s),
                [(Query(q=10 * s + i), Actual(q=10 * s + i)) for i in range(3)],
            )
            for s in range(2)
        ]


class Preparator0(Preparator):
    def __init__(self, params: PP):
        self.params = params

    def prepare(self, ctx, td: TrainingData) -> PreparedData:
        return PreparedData(td_id=td.id, p_id=self.params.id)


@dataclass
class Model0:
    algo_id: int
    td_id: int
    p_id: int


class Algo0(Algorithm):
    def __init__(self, params: AP):
        self.params = params

    def train(self, ctx, pd: PreparedData) -> Model0:
        return Model0(algo_id=self.params.id, td_id=pd.td_id, p_id=pd.p_id)

    def predict(self, model: Model0, query: Query) -> Prediction:
        return Prediction(
            q=query.q,
            algo_id=model.algo_id,
            td_id=model.td_id,
            p_id=model.p_id,
            supplemented=query.supplemented,
        )


class Algo1(Algo0):
    """Same behavior, distinct class for multi-algo binding tests."""


class NoParamsAlgo(Algorithm):
    """Zero-arg constructor → Doer's no-params path."""

    def train(self, ctx, pd: PreparedData) -> Model0:
        return Model0(algo_id=-1, td_id=pd.td_id, p_id=pd.p_id)

    def predict(self, model, query):
        return Prediction(
            q=query.q, algo_id=-1, td_id=model.td_id, p_id=model.p_id
        )


@dataclass
class PersistentModel0(LocalFileSystemPersistentModel):
    """User-managed persistence path (PersistentModelManifest mode)."""

    algo_id: int = 0
    td_id: int = 0
    p_id: int = 0


class PersistentAlgo(Algorithm):
    def __init__(self, params: AP):
        self.params = params

    def train(self, ctx, pd: PreparedData) -> PersistentModel0:
        return PersistentModel0(
            algo_id=self.params.id, td_id=pd.td_id, p_id=pd.p_id
        )

    def predict(self, model, query):
        return Prediction(
            q=query.q, algo_id=model.algo_id, td_id=model.td_id, p_id=model.p_id
        )


class UnserializableModel:
    """Defeats pickle → RetrainOnDeploy path."""

    def __init__(self, algo_id, td_id, p_id):
        self.algo_id, self.td_id, self.p_id = algo_id, td_id, p_id
        self.closure = lambda: None  # not picklable

    def __reduce__(self):
        raise pickle.PicklingError("deliberately unserializable")


class UnserializableAlgo(Algorithm):
    def __init__(self, params: AP):
        self.params = params

    def train(self, ctx, pd: PreparedData):
        return UnserializableModel(self.params.id, pd.td_id, pd.p_id)

    def predict(self, model, query):
        return Prediction(
            q=query.q, algo_id=model.algo_id, td_id=model.td_id, p_id=model.p_id
        )


class SupplementServing(Serving):
    """Stamps supplement + serves first prediction."""

    def supplement(self, query: Query) -> Query:
        return Query(q=query.q, supplemented=True)

    def serve(self, query, predictions):
        return predictions[0]


class SumServing(Serving):
    """Combines multi-algo predictions: sums algo ids."""

    def serve(self, query, predictions):
        p = predictions[0]
        return Prediction(
            q=p.q,
            algo_id=sum(x.algo_id for x in predictions),
            td_id=p.td_id,
            p_id=p.p_id,
            supplemented=p.supplemented,
        )


# -- engines ----------------------------------------------------------------


@dataclass
class SlowDSP:
    id: int = 0
    sleep_s: float = 30.0


class SlowDataSource(DataSource):
    """Sleeps through read_training — scheduler chaos tests kill the
    train subprocess while it sits here."""

    def __init__(self, params: SlowDSP):
        self.params = params

    def read_training(self, ctx):
        import time

        time.sleep(self.params.sleep_s)
        return TrainingData(id=self.params.id)


class Engine0Factory(EngineFactory):
    def apply(self):
        return Engine(
            DataSource0,
            Preparator0,
            {"algo0": Algo0, "algo1": Algo1, "noparams": NoParamsAlgo},
            {"": FirstServing, "sum": SumServing, "supp": SupplementServing},
        )


class PersistentEngineFactory(EngineFactory):
    def apply(self):
        return Engine(DataSource0, Preparator0, PersistentAlgo, FirstServing)


class SlowEngineFactory(EngineFactory):
    def apply(self):
        return Engine(SlowDataSource, Preparator0, Algo0, FirstServing)


class UnserializableEngineFactory(EngineFactory):
    def apply(self):
        return Engine(DataSource0, Preparator0, UnserializableAlgo, FirstServing)


# -- fleet-eval grid engine (ISSUE 20) --------------------------------------
# A jax-free engine with a real eval surface: configurable folds, a
# train_grid hook that stamps how many points shared its device program,
# and a deterministic score peaked at weight=0.37 so grid winners are
# known in advance. Evalfleet chaos/parity tests and bench.py use it.


@dataclass
class GridDSP:
    folds: int = 2
    queries: int = 4
    sleep_s: float = 0.0  # stall inside read_eval → kill lands mid-shard


class GridDataSource(DataSource):
    def __init__(self, params: GridDSP):
        self.params = params

    def read_training(self, ctx):
        return TrainingData(id=0)

    def read_eval(self, ctx):
        if self.params.sleep_s:
            import time

            time.sleep(self.params.sleep_s)
        return [
            (
                TrainingData(id=f),
                EvalInfo(id=f),
                [
                    (Query(q=100 * f + i), Actual(q=100 * f + i))
                    for i in range(self.params.queries)
                ],
            )
            for f in range(self.params.folds)
        ]


@dataclass
class GridAP:
    weight: float = 0.0
    # simulated device-program cost: train_grid pays it ONCE for the
    # whole params group (one program), train() pays it per point —
    # bench.py's grid-group speedup measures exactly this difference
    train_cost_s: float = 0.0


@dataclass
class GridModel:
    weight: float
    td_id: int
    grid_size: int = 1  # points trained in the same train_grid call


@dataclass
class GridPrediction:
    q: int
    score: float
    grid_size: int


class GridAlgo(Algorithm):
    BEST_WEIGHT = 0.37

    def __init__(self, params: GridAP):
        self.params = params

    @staticmethod
    def _spend(cost_s: float) -> None:
        if cost_s:
            import time

            time.sleep(cost_s)

    def train(self, ctx, pd) -> GridModel:
        self._spend(self.params.train_cost_s)
        return GridModel(self.params.weight, pd.td_id, 1)

    def train_grid(self, ctx, pd, params_list) -> list:
        self._spend(max(p.train_cost_s for p in params_list))
        return [
            GridModel(p.weight, pd.td_id, len(params_list))
            for p in params_list
        ]

    def predict(self, model: GridModel, query: Query) -> GridPrediction:
        return GridPrediction(
            q=query.q,
            score=1.0 - abs(model.weight - self.BEST_WEIGHT),
            grid_size=model.grid_size,
        )


class GridScore:
    """AverageMetric over GridPrediction.score (declared lazily so
    importing sample_engine needs no controller.metrics / numpy)."""

    def __new__(cls):
        from predictionio_tpu.controller.metrics import AverageMetric

        class _GridScore(AverageMetric):
            def header(self):
                return "GridScore"

            def calculate_one(self, q, p, a):
                return p.score

        return _GridScore()


class GridEngineFactory(EngineFactory):
    def apply(self):
        return Engine(GridDataSource, Preparator0, {"grid": GridAlgo},
                      FirstServing)
