"""Event model + DataMap + validation tests
(reference: DataMapSpec, EventJson4sSupport round-trips, EventValidation rules
in Event.scala:65-163)."""

import datetime as dt

import pytest

from predictionio_tpu.data import (
    DataMap,
    Event,
    ValidationError,
)
from predictionio_tpu.data.datamap import DataMapError

UTC = dt.timezone.utc


class TestDataMap:
    def test_typed_get(self):
        d = DataMap({"a": 1, "b": 2.5, "c": "x", "d": True, "e": [1, 2], "f": {"g": 1}})
        assert d.get("a", int) == 1
        assert d.get("b", float) == 2.5
        assert d.get("a", float) == 1.0  # int widens to float
        assert d.get("c", str) == "x"
        assert d.get("d", bool) is True
        assert d.get_list("e", int) == [1, 2]
        assert d.get("f", dict) == {"g": 1}

    def test_missing_and_null(self):
        d = DataMap({"a": None})
        with pytest.raises(DataMapError):
            d.get("missing", int)
        with pytest.raises(DataMapError):
            d.get("a", int)
        assert d.get_opt("missing", int) is None
        assert d.get_opt("a", int) is None
        assert d.get_or_else("missing", 7) == 7

    def test_type_mismatch(self):
        d = DataMap({"a": "notanint", "b": True})
        with pytest.raises(DataMapError):
            d.get("a", int)
        # bool must not silently coerce to int (json4s distinction)
        with pytest.raises(DataMapError):
            d.get("b", int)

    def test_merge_remove(self):
        d1 = DataMap({"a": 1, "b": 2})
        d2 = DataMap({"b": 3, "c": 4})
        assert (d1 + d2).to_dict() == {"a": 1, "b": 3, "c": 4}
        assert (d1 - ["a"]).to_dict() == {"b": 2}

    def test_datetime_parse(self):
        d = DataMap({"t": "2024-05-01T12:30:00.000Z"})
        t = d.get_datetime("t")
        assert t == dt.datetime(2024, 5, 1, 12, 30, tzinfo=UTC)

    def test_extract_dataclass(self):
        import dataclasses

        @dataclasses.dataclass
        class P:
            a: int
            b: str = "z"

        p = DataMap({"a": 5, "ignored": 1}).extract(P)
        assert p == P(5, "z")


class TestEventJson:
    def test_round_trip(self):
        e = Event(
            event="buy",
            entity_type="user",
            entity_id="u1",
            target_entity_type="item",
            target_entity_id="i1",
            properties=DataMap({"price": 9.99}),
            event_time=dt.datetime(2024, 1, 2, 3, 4, 5, tzinfo=UTC),
            tags=("a", "b"),
            pr_id="pr1",
        )
        e2 = Event.from_json(e.to_json())
        assert e2.event == "buy"
        assert e2.entity_id == "u1"
        assert e2.target_entity_id == "i1"
        assert e2.properties.get("price", float) == 9.99
        assert e2.event_time == e.event_time
        assert e2.tags == ("a", "b")
        assert e2.pr_id == "pr1"

    def test_defaults(self):
        e = Event.from_json('{"event":"view","entityType":"user","entityId":"3"}')
        assert e.properties.is_empty
        assert e.event_time.tzinfo is not None

    def test_missing_required(self):
        with pytest.raises(ValidationError):
            Event.from_json('{"event":"view","entityType":"user"}')
        with pytest.raises(ValidationError):
            Event.from_json('{"entityType":"user","entityId":"3"}')

    def test_timezone_preserved(self):
        # reference TestEvents includes non-UTC zone cases
        e = Event.from_json(
            '{"event":"view","entityType":"u","entityId":"1",'
            '"eventTime":"2024-01-01T00:00:00.000+08:00"}'
        )
        assert e.event_time.utcoffset() == dt.timedelta(hours=8)


class TestEventValidation:
    def test_reserved_event_name(self):
        with pytest.raises(ValidationError):
            Event(event="$custom", entity_type="user", entity_id="1")
        with pytest.raises(ValidationError):
            Event(event="pio_thing", entity_type="user", entity_id="1")

    def test_reserved_entity_type(self):
        with pytest.raises(ValidationError):
            Event(event="view", entity_type="pio_user", entity_id="1")
        # builtin pio_pr is allowed
        Event(event="predict", entity_type="pio_pr", entity_id="1")

    def test_special_events_allowed(self):
        Event(event="$set", entity_type="user", entity_id="1", properties={"a": 1})
        Event(event="$unset", entity_type="user", entity_id="1", properties={"a": None})
        Event(event="$delete", entity_type="user", entity_id="1")

    def test_unset_requires_properties(self):
        with pytest.raises(ValidationError):
            Event(event="$unset", entity_type="user", entity_id="1")

    def test_delete_forbids_properties(self):
        with pytest.raises(ValidationError):
            Event(event="$delete", entity_type="user", entity_id="1", properties={"a": 1})

    def test_special_event_forbids_target(self):
        with pytest.raises(ValidationError):
            Event(
                event="$set",
                entity_type="user",
                entity_id="1",
                target_entity_type="item",
                target_entity_id="2",
            )

    def test_target_pairing(self):
        with pytest.raises(ValidationError):
            Event(event="view", entity_type="u", entity_id="1", target_entity_id="2")
        with pytest.raises(ValidationError):
            Event(event="view", entity_type="u", entity_id="1", target_entity_type="item")

    def test_empty_fields(self):
        with pytest.raises(ValidationError):
            Event(event="", entity_type="u", entity_id="1")
        with pytest.raises(ValidationError):
            Event(event="view", entity_type="", entity_id="1")
        with pytest.raises(ValidationError):
            Event(event="view", entity_type="u", entity_id="")
