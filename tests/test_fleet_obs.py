"""Fleet observability plane (ISSUE 16): cross-process trace assembly
(stitching, orphan expiry, HTTP polling), recording-rule math vs the
direct TSDB queries, fleet-aggregated SLOs over instance-tagged series
(incl. the recorded fast path), exemplar retention bounds, and the
Monitor's alert→trace enrichment."""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from predictionio_tpu.obs import spans as _spans
from predictionio_tpu.obs import tracing as _tracing
from predictionio_tpu.obs.monitor import (
    FleetScraper,
    Monitor,
    SLOEngine,
    SLOSpec,
    record_slo_ratios,
    tenant_slo_presets,
)
from predictionio_tpu.obs.monitor.collector import TraceCollector
from predictionio_tpu.obs.monitor.scrape import parse_exemplar_lines
from predictionio_tpu.obs.monitor.slo import (
    RECORDED_RATIO,
    RECORDED_SAMPLES,
    error_fraction,
)
from predictionio_tpu.obs.monitor.tsdb import (
    TSDB,
    MetricsSampler,
    RecordingRule,
    bucket_quantile,
    evaluate_rules,
    load_recording_rules,
)
from predictionio_tpu.obs.registry import MetricsRegistry, render_families

T0 = 1_700_000_000.0


def _span(tid, sid, name, parent=None, start=T0, dur=0.01, attrs=None,
          error=False) -> dict:
    return _spans.Span(
        trace_id=tid, span_id=sid, name=name, parent_span_id=parent,
        start=start, duration=dur, attrs=dict(attrs or {}), error=error,
    ).to_dict()


# ---------------------------------------------------------------------------
# collector stitching
# ---------------------------------------------------------------------------


class TestCollectorStitching:
    def _collector(self, **kw) -> TraceCollector:
        base = dict(
            recorder=_spans.SpanRecorder(), interval_s=1.0, hold_s=5.0,
        )
        base.update(kw)
        return TraceCollector(**base)

    def test_hedged_two_attempt_trace_assembles_one_tree(self):
        """The acceptance shape: gateway root + primary/hedge attempt
        children + a replica-side server span arriving as a SEPARATE
        fragment stitch into one tree, kept for being hedged."""
        col = self._collector()
        tid = "a" * 16
        for sp in (
            _span(tid, "root", "gateway.request",
                  attrs={"server": "gateway", "path": "/queries.json"},
                  dur=0.2),
            _span(tid, "att1", "gateway.attempt", parent="root",
                  attrs={"kind": "primary", "replica": "r0",
                         "outcome": "200"}),
            _span(tid, "att2", "gateway.attempt", parent="root",
                  attrs={"kind": "hedge", "replica": "r1",
                         "outcome": "200"}),
        ):
            col._ingest(sp, T0)
        # the replica fragment lands on a LATER poll (cross-process)
        col._ingest(
            _span(tid, "srv1", "server.request", parent="att1",
                  attrs={"server": "query", "replica": "r0"}),
            T0 + 1,
        )
        col._settle(T0 + 1)
        assert col.status()["assembled"] == 1
        spans = col.get_trace(tid)
        assert len(spans) == 4
        by_id = {s["span_id"]: s for s in spans}
        assert by_id["att1"]["parent_span_id"] == "root"
        assert by_id["att2"]["parent_span_id"] == "root"
        assert by_id["srv1"]["parent_span_id"] == "att1"
        (row,) = col.summaries()
        assert row["kept"] == "hedged"
        assert set(row["servers"]) == {"gateway", "query"}
        assert row["spans"] == 4
        # perfetto export carries every span of the stitched tree
        export = col.perfetto_export(tid)
        names = [
            e["name"] for e in export["traceEvents"] if e["ph"] == "X"
        ]
        assert names.count("gateway.attempt") == 2

    def test_orphan_fragment_held_then_expired(self):
        """A fragment whose root never arrives (its process died before
        dumping) is held for hold_s, then dropped and counted — the
        fragment store cannot grow without bound."""
        col = self._collector(hold_s=5.0)
        col._ingest(
            _span("b" * 16, "child", "server.request", parent="gone",
                  error=True),
            T0,
        )
        col._settle(T0)
        st = col.status()
        assert st["pending_fragments"] == 1
        assert st["assembled"] == 0
        col._settle(T0 + 4.9)  # still inside the hold window
        assert col.status()["pending_fragments"] == 1
        col._settle(T0 + 5.1)
        st = col.status()
        assert st["pending_fragments"] == 0
        assert st["assembled"] == 0
        assert st["expired_orphans"] == 1

    def test_orphan_resolves_when_root_arrives_late(self):
        """Cross-process skew: the replica fragment is polled BEFORE
        the gateway fragment. The held orphan must join the trace when
        its root shows up within the hold window."""
        col = self._collector()
        tid = "c" * 16
        col._ingest(
            _span(tid, "srv", "server.request", parent="att"), T0
        )
        col._settle(T0)
        assert col.status()["pending_fragments"] == 1
        col._ingest(
            _span(tid, "root", "gateway.request", error=True, attrs={
                "server": "gateway",
            }),
            T0 + 2,
        )
        col._ingest(
            _span(tid, "att", "gateway.attempt", parent="root"), T0 + 2
        )
        col._settle(T0 + 2)
        assert col.status()["pending_fragments"] == 0
        assert len(col.get_trace(tid)) == 3

    def test_span_dedup_absorbs_poll_overlap(self):
        """Cursors deliberately re-cover one interval per poll; the
        span-id dedup must make the overlap free."""
        col = self._collector()
        tid = "d" * 16
        root = _span(tid, "root", "gateway.request", error=True)
        col._ingest(root, T0)
        col._ingest(dict(root), T0 + 1)  # same span, next poll
        col._settle(T0 + 1)
        assert len(col.get_trace(tid)) == 1

    def test_boring_trace_not_kept(self):
        """Tail sampling: a fast, error-free, unhedged trace is not
        worth fleet retention."""
        col = self._collector(slow_ms=1000.0)
        tid = "e" * 16
        col._ingest(_span(tid, "root", "gateway.request", dur=0.001), T0)
        col._ingest(
            _span(tid, "att", "gateway.attempt", parent="root",
                  attrs={"kind": "primary"}, dur=0.001),
            T0,
        )
        col._settle(T0 + 10)  # past hold: fragment either kept or gone
        st = col.status()
        assert st["assembled"] == 0
        assert st["pending_fragments"] == 0

    def test_http_polling_stitches_remote_fragments(self, fresh_storage):
        """The wire path: fragments recorded in a server process come
        back through `GET /debug/traces?spans=1&since=` and assemble."""
        from predictionio_tpu.data.api.server import (
            EventServer,
            EventServerConfig,
        )

        srv = EventServer(
            fresh_storage,
            EventServerConfig(ip="127.0.0.1", port=0, wal_dir=None),
        )
        port = srv.start()
        tid = "f" * 16
        # the server process's recorder is this process's default
        # recorder (same process in-test); the collector gets a PRIVATE
        # recorder so the only road to these spans is HTTP
        rec = _spans.get_default_recorder()
        rec.record(_spans.Span(
            trace_id=tid, span_id="root", name="gateway.request",
            parent_span_id=None, start=time.time(), duration=0.2,
            attrs={"server": "gateway"}, error=True,
        ), finalize=False)
        rec.record(_spans.Span(
            trace_id=tid, span_id="att", name="gateway.attempt",
            parent_span_id="root", start=time.time(), duration=0.1,
            attrs={"kind": "failover"},
        ), finalize=False)
        col = self._collector(
            targets=[("ev", f"http://127.0.0.1:{port}")],
        )
        try:
            ingested = col.collect_once()
        finally:
            srv.stop()
        assert ingested >= 2
        assert col.status()["polls"] == 1
        assert col.status()["poll_errors"] == 0
        assert len(col.get_trace(tid)) == 2

    def test_assembled_store_bounded(self):
        """max_traces is a hard cap: the oldest assembled trace falls
        off when one more arrives."""
        col = self._collector(max_traces=2)
        for i in range(3):
            tid = f"t{i}" + "0" * 14
            col._ingest(
                _span(tid, f"r{i}", "gateway.request", error=True,
                      start=T0 + i),
                T0 + i,
            )
            col._settle(T0 + i)
        assert col.status()["assembled"] == 2
        assert col.get_trace("t0" + "0" * 14) == []


# ---------------------------------------------------------------------------
# recording rules
# ---------------------------------------------------------------------------


def _feed_counter(db, name, labels, pairs):
    for t, v in pairs:
        db.add(name, labels, v, "counter", t)


class TestRecordingRules:
    def test_rate_rule_matches_direct_tsdb_rate(self):
        db = TSDB()
        _feed_counter(
            db, "http_requests_total", {"server": "q", "status": "200"},
            [(T0, 0.0), (T0 + 60, 120.0)],
        )
        rule = RecordingRule(
            record="q:rate1m", kind="rate",
            source="http_requests_total", window_s=60.0,
        )
        got = rule.evaluate(db, now=T0 + 60)
        want = db.rate("http_requests_total", None, 60.0, T0 + 60)
        assert got == pytest.approx(want) == pytest.approx(2.0)

    def test_error_ratio_rule_matches_hand_math(self):
        db = TSDB()
        _feed_counter(
            db, "http_requests_total", {"server": "q", "status": "200"},
            [(T0, 0.0), (T0 + 30, 80.0)],
        )
        _feed_counter(
            db, "http_requests_total", {"server": "q", "status": "500"},
            [(T0, 0.0), (T0 + 30, 20.0)],
        )
        rule = RecordingRule(
            record="q:err", kind="error_ratio",
            source="http_requests_total", window_s=60.0,
        )
        assert rule.evaluate(db, now=T0 + 30) == pytest.approx(0.2)
        # bad_values variant: exact label match instead of numeric >=
        rule2 = RecordingRule(
            record="q:err2", kind="error_ratio",
            source="http_requests_total", window_s=60.0,
            bad_values=("200",),
        )
        assert rule2.evaluate(db, now=T0 + 30) == pytest.approx(0.8)

    def test_quantile_rule_interpolates_buckets(self):
        db = TSDB()
        # 10 obs <= 0.1, 10 more in (0.1, 0.5]: p50 = 0.1, p75 = 0.3
        for le, cum in (("0.1", 10.0), ("0.5", 20.0), ("+Inf", 20.0)):
            _feed_counter(
                db, "http_request_seconds_bucket", {"le": le},
                [(T0, 0.0), (T0 + 30, cum)],
            )
        assert bucket_quantile(
            db, "http_request_seconds", 0.5, None, 60.0, T0 + 30
        ) == pytest.approx(0.1)
        assert bucket_quantile(
            db, "http_request_seconds", 0.75, None, 60.0, T0 + 30
        ) == pytest.approx(0.3)
        rule = RecordingRule(
            record="q:p75", kind="quantile",
            source="http_request_seconds", q=0.75, window_s=60.0,
        )
        assert rule.evaluate(db, now=T0 + 30) == pytest.approx(0.3)

    def test_quiet_window_writes_nothing(self):
        """None results (zero traffic) must NOT be stored — readers
        distinguish 'quiet' from 'zero'."""
        db = TSDB()
        rule = RecordingRule(
            record="q:err", kind="error_ratio",
            source="http_requests_total", window_s=60.0,
        )
        assert evaluate_rules(db, [rule], now=T0) == 0
        assert db.matching("q:err") == []

    def test_evaluate_rules_stores_first_class_series(self):
        db = TSDB()
        _feed_counter(
            db, "c_total", {"status": "500"},
            [(T0, 0.0), (T0 + 30, 5.0)],
        )
        _feed_counter(
            db, "c_total", {"status": "200"},
            [(T0, 0.0), (T0 + 30, 15.0)],
        )
        rule = RecordingRule(
            record="c:err", kind="error_ratio", source="c_total",
            window_s=60.0, labels=(("job", "q"),),
        )
        assert evaluate_rules(db, [rule], now=T0 + 30) == 1
        assert db.latest("c:err", {"job": "q"}) == pytest.approx(0.25)

    def test_rules_ride_the_sampler_tick(self):
        """post_sample runs after raw sampling on the SAME tick, and a
        raising hook never takes down raw sampling."""
        reg = MetricsRegistry()
        reg.counter("ticks_total", "t").inc(3.0)
        db = TSDB()
        calls = []

        def hook(tsdb, now):
            calls.append(now)
            raise RuntimeError("derived series must not kill sampling")

        sampler = MetricsSampler(
            db, reg.families, interval_s=60.0, post_sample=hook
        )
        written = sampler.sample_once(now=T0)
        assert written > 0
        assert calls == [T0]
        assert db.latest("ticks_total") == 3.0

    def test_load_rules_json_and_malformed(self, tmp_path):
        rules = load_recording_rules(json.dumps([{
            "record": "a:rate", "kind": "rate", "source": "a_total",
            "window_s": 30, "match": {"server": "q"},
        }]))
        assert len(rules) == 1
        assert rules[0].match == (("server", "q"),)
        # @file indirection
        p = tmp_path / "rules.json"
        p.write_text(json.dumps([{
            "record": "b:p99", "kind": "quantile", "source": "b",
        }]))
        assert len(load_recording_rules(f"@{p}")) == 1
        # malformed input degrades to [] (never takes the plane down)
        assert load_recording_rules("[{\"record\": ") == []
        assert load_recording_rules("") == []
        with pytest.raises(ValueError):
            RecordingRule(record="x", kind="nope", source="y")
        with pytest.raises(ValueError):
            RecordingRule.from_dict({
                "record": "x", "kind": "rate", "source": "y",
                "bogus_field": 1,
            })


# ---------------------------------------------------------------------------
# fleet-scoped SLOs
# ---------------------------------------------------------------------------


def _fleet_spec(**kw) -> SLOSpec:
    base = dict(
        name="fleet-avail", kind="availability", objective=0.99,
        server="query", route="/queries.json", aggregate="sum",
        fast_window_s=10.0, window_s=40.0, burn_threshold=1.0,
        min_samples=1, for_s=0.0,
    )
    base.update(kw)
    return SLOSpec(**base)


def _feed_instance(db, instance, t, ok, err):
    for status, v in (("200", ok), ("500", err)):
        db.add(
            "http_requests_total",
            {"server": "query", "path": "/queries.json",
             "status": status, "instance": instance},
            v, "counter", t,
        )


class _StubMetrics(BaseHTTPRequestHandler):
    """A stub replica: /metrics exposing counters the test mutates."""

    counters = {}

    def do_GET(self):
        lines = []
        for (status,), v in sorted(self.counters.items()):
            lines.append(
                'http_requests_total{server="query",'
                f'path="/queries.json",status="{status}"}} {v}'
            )
        body = ("\n".join(lines) + "\n").encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):  # quiet
        pass


class TestFleetSLO:
    def test_aggregate_sum_pools_the_fleet(self):
        db = TSDB()
        _feed_instance(db, "r0", T0, 0.0, 0.0)
        _feed_instance(db, "r1", T0, 0.0, 0.0)
        _feed_instance(db, "r0", T0 + 5, 90.0, 10.0)
        _feed_instance(db, "r1", T0 + 5, 100.0, 0.0)
        # a process-LOCAL series without the instance tag must be
        # excluded from fleet judgment
        db.add(
            "http_requests_total",
            {"server": "query", "path": "/queries.json", "status": "500"},
            1000.0, "counter", T0 + 5,
        )
        frac, n = error_fraction(db, _fleet_spec(), 10.0, T0 + 5)
        assert frac == pytest.approx(10.0 / 200.0)
        assert n == pytest.approx(200.0)

    def test_aggregate_mean_averages_per_instance(self):
        db = TSDB()
        _feed_instance(db, "r0", T0, 0.0, 0.0)
        _feed_instance(db, "r1", T0, 0.0, 0.0)
        _feed_instance(db, "r0", T0 + 5, 50.0, 50.0)   # 0.5 locally
        _feed_instance(db, "r1", T0 + 5, 1000.0, 0.0)  # 0.0 locally
        spec = _fleet_spec(aggregate="mean")
        frac, _n = error_fraction(db, spec, 10.0, T0 + 5)
        # mean of per-instance fractions — the busy healthy replica
        # must NOT dilute the small broken one (sum would give ~0.045)
        assert frac == pytest.approx(0.25)

    def test_up_kind_aggregate_watches_whole_fleet(self):
        db = TSDB()
        db.add("up", {"instance": "r0"}, 1.0, "gauge", T0)
        db.add("up", {"instance": "r1"}, 0.0, "gauge", T0)
        spec = _fleet_spec(kind="up", aggregate="mean", objective=0.9)
        frac, n = error_fraction(db, spec, 10.0, T0)
        assert frac == pytest.approx(0.5)
        assert n == 2.0

    def test_fleet_slo_fires_across_two_stub_replicas(self):
        """The satellite: scrape two stub replica processes' /metrics,
        aggregate, and fire on the pooled error budget."""

        class _A(_StubMetrics):
            counters = {("200",): 0.0, ("500",): 0.0}

        class _B(_StubMetrics):
            counters = {("200",): 0.0, ("500",): 0.0}

        servers = []
        for cls in (_A, _B):
            s = ThreadingHTTPServer(("127.0.0.1", 0), cls)
            threading.Thread(target=s.serve_forever, daemon=True).start()
            servers.append(s)
        try:
            db = TSDB()
            scraper = FleetScraper(db, [
                ("r0", f"http://127.0.0.1:{servers[0].server_port}"),
                ("r1", f"http://127.0.0.1:{servers[1].server_port}"),
            ], interval_s=60.0)
            assert scraper.scrape_once(now=T0) == {"r0": True, "r1": True}
            # induced error window: r0 starts failing hard
            _A.counters = {("200",): 10.0, ("500",): 90.0}
            _B.counters = {("200",): 100.0, ("500",): 0.0}
            scraper.scrape_once(now=T0 + 5)
            spec = _fleet_spec()
            engine = SLOEngine(db, [spec], registry=MetricsRegistry())
            burn, n = engine.burn_rate(spec, 10.0, now=T0 + 5)
            # 90 bad / 200 total over a 0.01 budget
            assert burn == pytest.approx(45.0)
            engine.evaluate_once(now=T0 + 5)
            engine.evaluate_once(now=T0 + 6)
            assert engine.status("fleet-avail").state == "firing"
        finally:
            for s in servers:
                s.shutdown()

    def test_recorded_fast_path_feeds_burn_rate(self):
        """With a fresh recorded ratio and NO raw series at all, the
        burn must come from the recorded point — proof the engine read
        the precomputed series instead of rescanning."""
        db = TSDB()
        spec = _fleet_spec()
        labels = {"slo": spec.name, "window": "fast"}
        db.add(RECORDED_RATIO, labels, 0.05, "gauge", T0)
        db.add(RECORDED_SAMPLES, labels, 500.0, "gauge", T0)
        engine = SLOEngine(db, [spec], registry=MetricsRegistry())
        engine.recorded_max_age_s = 30.0
        burn, n = engine.burn_rate(spec, spec.fast_window_s, now=T0 + 5)
        assert burn == pytest.approx(0.05 / spec.budget)
        assert n == 500.0
        # raw fallback still works when disabled
        engine.recorded_max_age_s = 0.0
        assert engine.burn_rate(spec, spec.fast_window_s, now=T0 + 5) \
            == (None, 0.0)

    def test_stale_recorded_point_falls_back_to_raw(self):
        """Freshness gate: a wedged sampler's old recorded point must
        not freeze alerting — the raw rescan takes over."""
        db = TSDB()
        spec = _fleet_spec()
        db.add(RECORDED_RATIO, {"slo": spec.name, "window": "fast"},
               0.5, "gauge", T0 - 500)
        db.add(RECORDED_SAMPLES, {"slo": spec.name, "window": "fast"},
               100.0, "gauge", T0 - 500)
        _feed_instance(db, "r0", T0 - 5, 0.0, 0.0)
        _feed_instance(db, "r0", T0, 100.0, 0.0)
        engine = SLOEngine(db, [spec], registry=MetricsRegistry())
        engine.recorded_max_age_s = 30.0
        burn, n = engine.burn_rate(spec, spec.fast_window_s, now=T0)
        assert burn == pytest.approx(0.0)  # raw says healthy
        assert n == pytest.approx(100.0)

    def test_record_slo_ratios_writes_ratio_and_samples(self):
        db = TSDB()
        spec = _fleet_spec()
        _feed_instance(db, "r0", T0 - 5, 0.0, 0.0)
        _feed_instance(db, "r0", T0, 96.0, 4.0)
        written = record_slo_ratios(db, [spec], now=T0)
        assert written == 4  # (ratio + samples) × (fast, slow)
        assert db.latest(
            RECORDED_RATIO, {"slo": spec.name, "window": "fast"}
        ) == pytest.approx(0.04)
        # quiet spec: samples written (observable quiet), no ratio
        quiet = _fleet_spec(name="quiet", route="/other.json")
        assert record_slo_ratios(db, [quiet], now=T0) == 2
        assert db.latest(
            RECORDED_RATIO, {"slo": "quiet", "window": "fast"}
        ) is None
        assert db.latest(
            RECORDED_SAMPLES, {"slo": "quiet", "window": "fast"}
        ) == 0.0

    def test_tenant_presets_derived_and_spec_roundtrip(self):
        presets = tenant_slo_presets(["acme", "beta"])
        names = [p.name for p in presets]
        assert names == [
            "tenant:acme:availability", "tenant:acme:latency",
            "tenant:beta:availability", "tenant:beta:latency",
        ]
        for p in presets:
            # presets must survive the to_dict/from_dict wire format
            assert SLOSpec.from_dict(p.to_dict()) == p
        # aggregate survives the round trip too
        spec = _fleet_spec()
        assert SLOSpec.from_dict(spec.to_dict()).aggregate == "sum"
        with pytest.raises(ValueError):
            _fleet_spec(aggregate="median")


# ---------------------------------------------------------------------------
# exemplars
# ---------------------------------------------------------------------------


class TestExemplars:
    def _observe(self, fam, tid, value):
        tok = _tracing.set_trace_id(tid)
        try:
            fam.observe(value, path="/q")
        finally:
            _tracing.reset_trace_id(tok)

    def test_retention_bounded_keep_slowest(self, monkeypatch):
        monkeypatch.setenv("PIO_TRACE_EXEMPLARS", "3")
        reg = MetricsRegistry()
        fam = reg.histogram(
            "t_seconds", "t", buckets=(0.1, 1.0), labelnames=("path",)
        )
        for i, v in enumerate((0.5, 0.1, 0.9, 0.3, 2.0)):
            self._observe(fam, f"tid{i}", v)
        ex = fam.exemplars()
        assert len(ex) == 3
        assert [e["value"] for e in ex] == [2.0, 0.9, 0.5]
        # a faster value than the floor is not admitted
        self._observe(fam, "tid-fast", 0.01)
        assert len(fam.exemplars()) == 3
        # same trace id keeps only its own max (one slot per trace)
        self._observe(fam, "tid4", 5.0)
        self._observe(fam, "tid4", 0.2)
        ex = fam.exemplars()
        assert [e["trace_id"] for e in ex].count("tid4") == 1
        assert ex[0]["value"] == 5.0

    def test_untraced_observations_record_no_exemplar(self):
        reg = MetricsRegistry()
        fam = reg.histogram("u_seconds", "u", buckets=(1.0,))
        fam.observe(0.5)  # no ambient trace id
        assert fam.exemplars() == []

    def test_exposition_roundtrip(self, monkeypatch):
        monkeypatch.setenv("PIO_TRACE_EXEMPLARS", "4")
        reg = MetricsRegistry()
        fam = reg.histogram(
            "r_seconds", "r", buckets=(1.0,), labelnames=("path",)
        )
        self._observe(fam, "tidA", 0.25)
        text = render_families([fam])
        assert "# EXEMPLAR r_seconds tidA" in text
        parsed = parse_exemplar_lines(text)
        # ISSUE 17: lines now carry the observing label set as a
        # trailing compact-JSON token
        assert parsed == [("r_seconds", "tidA", 0.25, pytest.approx(
            parsed[0][3]
        ), {"path": "/q"})]
        # legacy 6-token lines (no labels json) still parse
        legacy = parse_exemplar_lines("# EXEMPLAR r_seconds tidB 0.5 1.0")
        assert legacy == [("r_seconds", "tidB", 0.5, 1.0, {})]
        # plain exposition parsing still works on the same text (the
        # exemplar comments are invisible to a vanilla scraper)
        from predictionio_tpu.obs.monitor.scrape import (
            parse_prometheus_text,
        )
        names = {n for n, _l, _v in parse_prometheus_text(text)}
        assert "r_seconds_count" in names

    def test_monitor_index_bounded_and_merged(self):
        monitor = Monitor()
        cap = monitor._exemplar_cap
        for i in range(cap + 10):
            monitor.note_exemplar("f_seconds", f"t{i}", float(i), ts=T0)
        ex = monitor.exemplars("f_seconds", limit=cap + 10)
        assert len(ex) == cap
        # keep-slowest: the earliest (fastest) entries were evicted
        assert ex[0]["value"] == float(cap + 9)

    def test_alert_enrichment_links_exemplars_and_traces(self):
        """A firing alert payload carries exemplar trace ids and the
        slowest assembled fleet traces — the alert→trace loop."""
        monitor = Monitor()
        monitor.note_exemplar("http_request_seconds", "tid-slow", 1.5,
                              ts=T0)
        col = TraceCollector(recorder=_spans.SpanRecorder())
        col._ingest(_span("g" * 16, "root", "gateway.request",
                          error=True, dur=0.4), T0)
        col._settle(T0)
        monitor.set_collector(col)
        row = {"slo": "fleet-avail", "state": "firing"}
        monitor._enrich_alert(row)
        assert row["exemplars"][0]["trace_id"] == "tid-slow"
        assert row["fleet_traces"][0]["trace_id"] == "g" * 16
