"""Pallas windowed-pass kernel vs the XLA scan path (interpret mode).

The fused kernel (ops/windowed_pallas.py) must agree with the chunked
XLA one-hot reduction (ops/windowed.windowed_gram_b) on identical
inputs; on CPU the kernel runs through the Pallas interpreter. This is
the equivalence contract behind the PIO_PALLAS_WINDOWED dispatch."""

import numpy as np
import pytest

import jax.numpy as jnp

from predictionio_tpu.ops.windowed import (
    BLOCK_EDGES,
    CHUNK_BLOCKS,
    WINDOW_ROWS,
    plan_windows,
    resolve_pallas_mode,
    windowed_gram_b,
)


def _staged_edge_pass(rng, n_src, n_dst, n_edges):
    """Plan a random edge list and return windowed_gram_b's arguments."""
    src = rng.integers(0, n_src, n_edges)
    dst = np.sort(rng.integers(0, n_dst, n_edges))
    vals = rng.uniform(0.5, 5.0, n_edges).astype(np.float32)
    plan = plan_windows(dst, n_dst)
    factors = rng.normal(size=(n_src, 8)).astype(np.float32)
    w_b = plan.take(vals)
    w_g = plan.take((1.0 + vals).astype(np.float32))
    return (
        jnp.asarray(factors),
        jnp.asarray(plan.take(src.astype(np.int32))).astype(jnp.int32),
        jnp.asarray(w_b),
        jnp.asarray(w_g),
        jnp.asarray(plan.chunked_local()),
        jnp.asarray(plan.block_window),
        plan.n_windows,
    )


@pytest.mark.parametrize("n_edges", [1, 500, 5000])
def test_interpret_matches_xla(n_edges):
    rng = np.random.default_rng(7)
    args = _staged_edge_pass(rng, n_src=60, n_dst=300, n_edges=n_edges)
    b_xla, g_xla = windowed_gram_b(*args, pallas=None)
    b_pl, g_pl = windowed_gram_b(*args, pallas="interpret")
    np.testing.assert_allclose(b_pl, b_xla, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(g_pl, g_xla, rtol=1e-5, atol=1e-5)


def test_multi_chunk_edge_pass():
    """More edges than one chunk holds → multiple scan steps / a grid
    spanning chunk-padding blocks (zero-weight blocks carrying the last
    real window's id)."""
    rng = np.random.default_rng(11)
    n_edges = CHUNK_BLOCKS * BLOCK_EDGES + 777  # forces n_chunks == 2
    args = _staged_edge_pass(rng, n_src=40, n_dst=4 * WINDOW_ROWS, n_edges=n_edges)
    b_xla, g_xla = windowed_gram_b(*args, pallas=None)
    b_pl, g_pl = windowed_gram_b(*args, pallas="interpret")
    np.testing.assert_allclose(b_pl, b_xla, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(g_pl, g_xla, rtol=1e-4, atol=1e-4)


def test_train_end_to_end_interpret(monkeypatch):
    """Full ALS train through the interpreted kernel == XLA-path train."""
    from predictionio_tpu.models import als

    rng = np.random.default_rng(3)
    n_users, n_items, n_edges = 50, 30, 400
    rows = rng.integers(0, n_users, n_edges).astype(np.int32)
    cols = rng.integers(0, n_items, n_edges).astype(np.int32)
    vals = rng.uniform(1, 5, n_edges).astype(np.float32)
    params = als.ALSParams(rank=4, iterations=2, cg_iterations=2)

    monkeypatch.setenv("PIO_PALLAS_WINDOWED", "0")
    ref = als.train(rows, cols, vals, n_users, n_items, params)
    monkeypatch.setenv("PIO_PALLAS_WINDOWED", "interpret")
    got = als.train(rows, cols, vals, n_users, n_items, params)
    np.testing.assert_allclose(
        got.user_factors, ref.user_factors, rtol=1e-4, atol=1e-4
    )
    np.testing.assert_allclose(
        got.item_factors, ref.item_factors, rtol=1e-4, atol=1e-4
    )


def test_resolve_pallas_mode(monkeypatch):
    monkeypatch.setenv("PIO_PALLAS_WINDOWED", "0")
    assert resolve_pallas_mode("auto") is None
    monkeypatch.setenv("PIO_PALLAS_WINDOWED", "interpret")
    assert resolve_pallas_mode("auto") == "interpret"
    monkeypatch.delenv("PIO_PALLAS_WINDOWED")
    # on the CPU test platform "auto"/"1" must fall back to the XLA path
    assert resolve_pallas_mode("auto") is None
    assert resolve_pallas_mode("1") is None
    assert resolve_pallas_mode("off") is None


def test_sharded_pallas_matches_single_device(monkeypatch):
    """VERDICT r4 #2: P > 1 no longer silently downgrades to the XLA
    path — the kernel runs shard_map'd over dp (local pallas scans +
    one psum) and must train identical factors to the single-device
    interpret run."""
    from predictionio_tpu.models import als
    from predictionio_tpu.parallel.mesh import make_mesh

    monkeypatch.setenv("PIO_PALLAS_WINDOWED", "interpret")
    rng = np.random.default_rng(3)
    n_users, n_items, n_edges = 300, 180, 5000
    rows = rng.integers(0, n_users, n_edges).astype(np.int32)
    cols = rng.integers(0, n_items, n_edges).astype(np.int32)
    vals = rng.uniform(0.5, 5.0, n_edges).astype(np.float32)
    p = als.ALSParams(rank=8, iterations=4)

    single = als.train(rows, cols, vals, n_users, n_items, p)
    mesh = make_mesh()  # the conftest 8-device CPU mesh
    assert mesh.devices.size > 1
    sharded = als.train(rows, cols, vals, n_users, n_items, p, mesh=mesh)
    np.testing.assert_allclose(
        sharded.user_factors, single.user_factors, rtol=2e-4, atol=1e-5
    )
    np.testing.assert_allclose(
        sharded.item_factors, single.item_factors, rtol=2e-4, atol=1e-5
    )


def test_grid_runs_pallas_and_matches_per_point(monkeypatch):
    """VERDICT r4 #2: train_grid no longer excludes the kernel — the
    vmapped pallas grid must equal per-point pallas runs (the kernel
    has no cross-grid-step state, so the batching rule is sound)."""
    from predictionio_tpu.models import als

    monkeypatch.setenv("PIO_PALLAS_WINDOWED", "interpret")
    rng = np.random.default_rng(4)
    n_users, n_items, n_edges = 200, 120, 3000
    rows = rng.integers(0, n_users, n_edges).astype(np.int32)
    cols = rng.integers(0, n_items, n_edges).astype(np.int32)
    vals = rng.uniform(0.5, 5.0, n_edges).astype(np.float32)
    lams = (0.01, 0.3)
    grid = als.train_grid(
        rows, cols, vals, n_users, n_items,
        [als.ALSParams(rank=6, iterations=3, lambda_=lam) for lam in lams],
    )
    for lam, m in zip(lams, grid):
        one = als.train(
            rows, cols, vals, n_users, n_items,
            als.ALSParams(rank=6, iterations=3, lambda_=lam),
        )
        np.testing.assert_allclose(
            m.user_factors, one.user_factors, rtol=1e-4, atol=1e-5
        )
        np.testing.assert_allclose(
            m.item_factors, one.item_factors, rtol=1e-4, atol=1e-5
        )
