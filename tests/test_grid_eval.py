"""Grid-batched tuning (VERDICT r2 #9): an N-point hyperparameter grid
trains as one device program per fold instead of N sequential trains."""

import time

import numpy as np
import pytest

from predictionio_tpu.controller import Engine, EngineParams, RuntimeContext
from predictionio_tpu.controller.dase import IdentityPreparator
from predictionio_tpu.controller.engine import resolve_engine
from predictionio_tpu.core.base import WorkflowParams
from predictionio_tpu.engines.classification.engine import (
    ClassificationEngine,
    LogisticRegressionParams,
    NaiveBayesParams,
)
from predictionio_tpu.models import classify, linreg


def _synth(n=3000, d=24, c=4, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.rand(c, d).astype(np.float32) * 3
    y = rng.randint(0, c, n).astype(np.int32)
    x = np.abs(centers[y] + rng.rand(n, d).astype(np.float32))
    return x, y


class TestGridKernels:
    def test_nb_grid_matches_sequential(self):
        x, y = _synth()
        lams = [0.1, 0.5, 1.0, 2.0]
        grid = classify.train_naive_bayes_grid(x, y, 4, lams)
        for lam, m in zip(lams, grid):
            ref = classify.train_naive_bayes(x, y, 4, lam)
            np.testing.assert_allclose(m.log_prior, ref.log_prior, rtol=1e-5)
            np.testing.assert_allclose(
                m.log_likelihood, ref.log_likelihood, rtol=1e-5
            )

    def test_lr_grid_matches_sequential(self):
        x, y = _synth(n=800, d=10)
        grid_pts = [(0.3, 1e-4), (0.5, 1e-3), (0.8, 1e-2)]
        grid = classify.train_logistic_regression_grid(
            x, y, 4, grid_pts, iterations=60
        )
        for (lr, l2), m in zip(grid_pts, grid):
            ref = classify.train_logistic_regression(
                x, y, 4, iterations=60, lr=lr, l2=l2
            )
            np.testing.assert_allclose(
                m.weights, ref.weights, rtol=1e-4, atol=1e-5
            )

    def test_linreg_grid_matches_sequential(self):
        rng = np.random.RandomState(1)
        x = rng.rand(500, 8).astype(np.float32)
        yv = (x @ rng.rand(8).astype(np.float32) + 0.3).astype(np.float32)
        l2s = [1e-6, 1e-3, 1e-1]
        grid = linreg.train_linear_regression_grid(x, yv, l2s)
        for l2, m in zip(l2s, grid):
            ref = linreg.train_linear_regression(x, yv, l2=l2)
            np.testing.assert_allclose(m.weights, ref.weights, rtol=1e-4)
            assert m.intercept == pytest.approx(ref.intercept, rel=1e-3)


class TestALSGrid:
    """ALS (λ, α) grids share one staged WindowPlan (VERDICT r3 #6)."""

    @staticmethod
    def _edges(n_users=80, n_items=50, n_edges=1500, seed=5):
        rng = np.random.RandomState(seed)
        return (
            rng.randint(0, n_users, n_edges).astype(np.int32),
            rng.randint(0, n_items, n_edges).astype(np.int32),
            rng.randint(1, 6, n_edges).astype(np.float32),
            n_users,
            n_items,
        )

    def test_grid_matches_sequential(self):
        from predictionio_tpu.models import als

        rows, cols, vals, nu, ni = self._edges()
        grid_pts = [(0.01, 1.0), (0.1, 1.0), (0.01, 4.0), (1.0, 0.5)]
        params_list = [
            als.ALSParams(rank=6, iterations=3, lambda_=lam, alpha=a)
            for lam, a in grid_pts
        ]
        grid = als.train_grid(rows, cols, vals, nu, ni, params_list)
        for p, m in zip(params_list, grid):
            ref = als.train(rows, cols, vals, nu, ni, p)
            np.testing.assert_allclose(
                m.user_factors, ref.user_factors, rtol=2e-4, atol=2e-5
            )
            np.testing.assert_allclose(
                m.item_factors, ref.item_factors, rtol=2e-4, atol=2e-5
            )

    def test_rank_axis_grid_matches_serial(self):
        """VERDICT r4 #7: rank×λ grids share one staging — per-rank
        groups launch batched λ solves and every point must equal its
        serial train exactly."""
        from predictionio_tpu.models import als

        rows, cols, vals, nu, ni = self._edges()
        params_list = [
            als.ALSParams(rank=r, iterations=3, lambda_=lam)
            for r in (6, 8)
            for lam in (0.01, 0.3)
        ]
        grid = als.train_grid(rows, cols, vals, nu, ni, params_list)
        for p, m in zip(params_list, grid):
            assert m.user_factors.shape == (nu, p.rank)
            ref = als.train(rows, cols, vals, nu, ni, p)
            np.testing.assert_allclose(
                m.user_factors, ref.user_factors, rtol=2e-4, atol=2e-4
            )
            np.testing.assert_allclose(
                m.item_factors, ref.item_factors, rtol=2e-4, atol=2e-4
            )

    def test_rank_grid_supports_too_high_rank_rejection(self):
        from predictionio_tpu.models import als

        rows, cols, vals, nu, ni = self._edges()
        with pytest.raises(ValueError):
            als.train_grid(
                rows, cols, vals, nu, ni,
                [als.ALSParams(rank=40, iterations=2)],
            )

    def test_grid_beats_sequential(self):
        """Shared staging + one batched program must beat 4 sequential
        trains. On the CPU test platform the device work dominates and
        wall-clock is noisy, so the bar here is only 'strictly faster';
        the real bar lives in bench.py (als_grid_speedup_4pt, TPU): the
        same 4-point grid at 1M edges measures 4.3x on v5e (grid 2.26s
        vs 9.76s sequential — VERDICT r3 #6's ≥2x done-bar)."""
        from predictionio_tpu.models import als

        rows, cols, vals, nu, ni = self._edges(
            n_users=400, n_items=200, n_edges=40_000
        )
        params_list = [
            als.ALSParams(rank=8, iterations=4, lambda_=lam)
            for lam in (0.003, 0.01, 0.1, 1.0)
        ]
        # warm both compile caches so the comparison is run-time only
        als.train_grid(rows, cols, vals, nu, ni, params_list)
        als.train(rows, cols, vals, nu, ni, params_list[0])

        t0 = time.perf_counter()
        als.train_grid(rows, cols, vals, nu, ni, params_list)
        t_grid = time.perf_counter() - t0
        t0 = time.perf_counter()
        for p in params_list:
            als.train(rows, cols, vals, nu, ni, p)
        t_seq = time.perf_counter() - t0
        # 10% tolerance: strict wall-clock inequality on a shared CI host
        # is flake-prone (ADVICE r4); the real ≥2x bar is measured on TPU
        # in bench.py (als_grid_speedup_4pt)
        assert t_grid < 1.1 * t_seq, (
            f"grid {t_grid:.3f}s vs sequential {t_seq:.3f}s "
            f"({t_seq / t_grid:.2f}x)"
        )


# -- engine-level grid batching ---------------------------------------------


def _grid_eps(n_points):
    """LR grid over lr values; iterations fixed (the static loop bound)."""
    return [
        EngineParams(
            data_source_params=("", None),
            algorithm_params_list=(
                (
                    "lr",
                    LogisticRegressionParams(
                        iterations=150, lr=0.2 + 0.05 * i, l2=1e-4
                    ),
                ),
            ),
            serving_params=("", None),
        )
        for i in range(n_points)
    ]


class _ArrayDataSource:
    """In-memory DASE data source over a fixed (x, y) eval fold."""

    def __init__(self, params=None):
        pass

    X, Y = _synth(n=4000, d=32, c=4, seed=3)

    def read_training(self, ctx):
        return self._td()

    def _td(self):
        from predictionio_tpu.engines.classification.engine import TrainingData

        return TrainingData(
            features=self.X, labels=self.Y,
            label_vocab=tuple(f"c{i}" for i in range(4)),
        )

    def read_eval(self, ctx):
        from predictionio_tpu.engines.classification.engine import (
            ActualResult,
            Query,
        )

        qa = [
            (Query(features=self.X[i].tolist()),
             ActualResult(label=f"c{self.Y[i]}"))
            for i in range(0, 200)
        ]
        return [(self._td(), {"fold": 0}, qa)]


def _make_engine():
    from predictionio_tpu.engines.classification.engine import (
        LogisticRegressionAlgorithm,
    )
    from predictionio_tpu.controller import FirstServing

    return Engine(
        _ArrayDataSource,
        IdentityPreparator,
        {"lr": LogisticRegressionAlgorithm},
        FirstServing,
    )


class TestEngineGridBatching:
    def test_grid_path_activates_and_matches_serial(self):
        engine = _make_engine()
        ctx = RuntimeContext(mode="eval")
        eps = _grid_eps(3)
        assert engine._grid_batchable(ctx, eps)
        batched = engine.batch_eval(ctx, eps)

        serial_engine = _make_engine()
        serial_engine._grid_batchable = lambda *_a: False
        serial = serial_engine.batch_eval(ctx, eps)

        for (ep_b, res_b), (ep_s, res_s) in zip(batched, serial):
            labels_b = [p.label for _ei, qpa in res_b for _q, p, _a in qpa]
            labels_s = [p.label for _ei, qpa in res_s for _q, p, _a in qpa]
            assert labels_b == labels_s

    def test_mixed_grid_falls_back_to_serial(self):
        engine = _make_engine()
        eps = _grid_eps(2)
        # different iterations → LR train_grid itself falls back; but a
        # MULTI-algorithm grid must not take the grid path at all
        multi = [
            ep.copy(
                algorithm_params_list=ep.algorithm_params_list * 2
            )
            for ep in eps
        ]
        assert not engine._grid_batchable(RuntimeContext(mode='eval'), multi)

    def test_8_point_grid_speedup(self):
        """VERDICT acceptance: >=2x faster than N sequential trains on an
        8-point grid (after warming both compiled programs)."""
        engine = _make_engine()
        ctx = RuntimeContext(mode="eval")
        eps = _grid_eps(8)

        serial_engine = _make_engine()
        serial_engine._grid_batchable = lambda *_a: False

        # warm both paths (compile)
        engine.batch_eval(ctx, eps)
        serial_engine.batch_eval(ctx, eps)

        t0 = time.perf_counter()
        engine.batch_eval(ctx, eps)
        t_grid = time.perf_counter() - t0
        t0 = time.perf_counter()
        serial_engine.batch_eval(ctx, eps)
        t_serial = time.perf_counter() - t0
        assert t_serial / t_grid >= 2.0, (
            f"grid {t_grid:.3f}s vs serial {t_serial:.3f}s "
            f"({t_serial / t_grid:.2f}x)"
        )

    def test_eval_wall_clock_recorded(self):
        from predictionio_tpu.controller.evaluation import (
            Evaluation,
            MetricEvaluator,
        )
        from predictionio_tpu.controller.metrics import AverageMetric
        from predictionio_tpu.data.storage.registry import (
            SourceConfig,
            Storage,
            StorageConfig,
        )
        from predictionio_tpu.workflow.evaluation import run_evaluation

        class Acc(AverageMetric):
            def calculate_one(self, q, p, a):
                return 1.0 if p.label == a.label else 0.0

        class Ev(Evaluation):
            def __init__(self):
                self.engine = _make_engine()
                self.metric = Acc()

        storage = Storage(StorageConfig(
            sources={"MEM": SourceConfig("MEM", "memory", {})},
            repositories={
                "METADATA": "MEM", "EVENTDATA": "MEM", "MODELDATA": "MEM",
            },
        ))
        inst, result = run_evaluation(storage, Ev(), _grid_eps(3))
        assert inst.status == "EVALCOMPLETED"
        assert float(inst.env["eval_wall_sec"]) > 0
        assert inst.env["grid_points"] == "3"
