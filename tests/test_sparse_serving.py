"""Sparse serving-time filtering (VERDICT r1 #7): top-k with candidate
sets / sparse exclusion instead of dense item-space masks, validated
against the dense reference implementation and at a 10^5-item catalog."""

import numpy as np

from predictionio_tpu.models import ranking


def dense_reference(scores, k, exclude_idx=None, include_idx=None,
                    positive_only=False):
    """The old dense-mask path, kept here as the oracle."""
    excluded = np.zeros(len(scores), dtype=bool)
    if include_idx is not None:
        keep = np.zeros(len(scores), dtype=bool)
        keep[np.asarray(include_idx, dtype=np.int64)] = True
        excluded |= ~keep
    if exclude_idx is not None and len(exclude_idx):
        excluded[np.asarray(exclude_idx, dtype=np.int64)] = True
    if positive_only:
        excluded |= scores <= 0.0
    masked = ranking.exclusion_scores(scores, excluded)
    return ranking.top_k_indices(masked, k)


class TestTopKFiltered:
    def _scores(self, n, seed=0):
        rng = np.random.RandomState(seed)
        # distinct values so ordering is unambiguous
        return rng.permutation(n).astype(np.float32) - n / 3.0

    def test_matches_dense_no_filters(self):
        s = self._scores(500)
        got = ranking.top_k_filtered(s, 10)
        np.testing.assert_array_equal(got, dense_reference(s, 10))

    def test_matches_dense_with_exclusions(self):
        s = self._scores(500, seed=1)
        rng = np.random.RandomState(2)
        ex = rng.choice(500, 60, replace=False)
        got = ranking.top_k_filtered(s, 10, exclude_idx=ex)
        np.testing.assert_array_equal(got, dense_reference(s, 10, ex))

    def test_matches_dense_with_whitelist(self):
        s = self._scores(500, seed=3)
        rng = np.random.RandomState(4)
        inc = rng.choice(500, 40, replace=False)
        ex = inc[:5]
        got = ranking.top_k_filtered(s, 10, exclude_idx=ex, include_idx=inc)
        np.testing.assert_array_equal(
            got, dense_reference(s, 10, ex, inc)
        )

    def test_matches_dense_positive_only(self):
        s = self._scores(300, seed=5)
        got = ranking.top_k_filtered(s, 20, positive_only=True)
        np.testing.assert_array_equal(
            got, dense_reference(s, 20, positive_only=True)
        )
        assert (s[got] > 0).all()

    def test_excluded_top_items_are_replaced(self):
        """Excluding the entire natural top-k must surface the next k."""
        s = np.arange(100, dtype=np.float32)
        ex = np.arange(90, 100)  # the 10 best
        got = ranking.top_k_filtered(s, 10, exclude_idx=ex)
        np.testing.assert_array_equal(got, np.arange(89, 79, -1))

    def test_duplicate_exclusions_and_unknown_ids(self):
        s = self._scores(100, seed=6)
        ex = [5, 5, 7, 7, 7]
        got = ranking.top_k_filtered(s, 5, exclude_idx=ex)
        np.testing.assert_array_equal(got, dense_reference(s, 5, [5, 7]))

    def test_catalog_scale_100k(self):
        """10^5-item catalog, 2k-item history: sparse path must agree with
        the dense oracle and never allocate an item-space bool mask."""
        n = 100_000
        s = self._scores(n, seed=7)
        rng = np.random.RandomState(8)
        ex = rng.choice(n, 2000, replace=False)
        got = ranking.top_k_filtered(s, 20, exclude_idx=ex)
        np.testing.assert_array_equal(got, dense_reference(s, 20, ex))

    def test_empty_whitelist_returns_empty(self):
        s = self._scores(50)
        got = ranking.top_k_filtered(s, 5, include_idx=np.empty(0, np.int64))
        assert len(got) == 0


class TestECommSparseFilters:
    def test_combined_filters_at_scale(self):
        """ecommerce-style combined category whitelist + blacklist +
        seen-exclusion on a 100k catalog, vs the dense oracle."""
        n = 100_000
        rng = np.random.RandomState(9)
        scores = rng.standard_normal(n).astype(np.float32)
        cat_items = np.sort(rng.choice(n, 30_000, replace=False))
        seen = rng.choice(cat_items, 500, replace=False)
        blacklist = rng.choice(n, 50, replace=False)
        ex = np.concatenate([seen, blacklist])
        got = ranking.top_k_filtered(
            scores, 10, exclude_idx=ex, include_idx=cat_items
        )
        np.testing.assert_array_equal(
            got, dense_reference(scores, 10, ex, cat_items)
        )
