"""Replicated event store units (ISSUE 19): frame protocol idempotence
and gap handling, torn-frame-at-the-epoch-boundary recovery, resumable
hash-verified segment shipping across a follower restart, epoch fencing
and fenced promotion, the CAS election, and the replica read routing
fold-in consumers use."""

import glob
import json
import os
import threading

import pytest

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import StorageError
from predictionio_tpu.data.storage.replication import (
    FollowerLink,
    ReplicaEventStore,
    ReplicaReadStorage,
    ReplicationConfig,
    SegmentShipper,
    elect_and_promote,
)
from predictionio_tpu.data.storage.segmentfs import SegmentFSEventStore
from predictionio_tpu.fleet.election import CasElection
from predictionio_tpu.obs.registry import MetricsRegistry

APP = 7


def _mem_storage():
    from predictionio_tpu.data.storage.registry import (
        SourceConfig,
        Storage,
        StorageConfig,
    )

    return Storage(StorageConfig(
        sources={"M": SourceConfig("M", "memory", {})},
        repositories={
            "METADATA": "M", "EVENTDATA": "M", "MODELDATA": "M",
        },
    ))


def _row(k):
    """A valid segmentfs event row (the shape ship_tail_after emits):
    [id, event, etype, eid, ttype, tid, props, t_ms, tags, prid, ct_ms]"""
    return [
        f"e{k}", "rate", "user", f"u{k}", "item", "i1",
        {"rating": 1.0}, 0, None, None, 0,
    ]


def _ev(k, u=None):
    return Event(
        event="rate", entity_type="user", entity_id=u or f"u{k}",
        target_entity_type="item", target_entity_id=f"i{k % 5}",
        properties={"rating": float(k % 5 + 1)},
    )


def _store_cfg(tmp, name, **over):
    cfg = {
        "PATH": str(tmp / name),
        # seals are driven explicitly in these tests
        "SEAL_INTERVAL_S": "3600", "SEAL_AGE_S": "3600",
        "SEAL_EVENTS": "1000000",
        "METRICS_REGISTRY": MetricsRegistry(),
    }
    cfg.update(over)
    return cfg


def _primary(tmp, **over):
    s = SegmentFSEventStore(_store_cfg(tmp, "primary", **over))
    s.init_app(APP)
    return s


def _replica(tmp, **over):
    r = ReplicaEventStore(_store_cfg(tmp, "replica", **over))
    r.init_app(APP)
    return r


class _DirectLink:
    """FollowerLink stand-in calling a replica in-process (same method
    surface the daemon's `replication` DAO exposes), with an optional
    call log so tests can assert what a resumed ship re-sent."""

    def __init__(self, replica, name="direct:0"):
        self.replica = replica
        self.name = name
        self.lock = threading.Lock()
        self.calls = []

    def call(self, method, *args, **kwargs):
        self.calls.append(method)
        return getattr(self.replica, method)(*args, **kwargs)


def _shipper(primary, replica, epoch=1, **over):
    cfg = ReplicationConfig(followers=("direct:0",), **over)
    sh = SegmentShipper(
        primary, cfg, epoch=epoch, metrics=MetricsRegistry()
    )
    sh.links = [_DirectLink(replica)]
    return sh


def _revs(store, app=APP):
    return [e.revision for e in store.find_since(app, 0)]


# ---------------------------------------------------------------------------
# frame protocol
# ---------------------------------------------------------------------------


class TestFrameProtocol:
    def test_ship_and_apply_parity(self, tmp_path):
        primary = _primary(tmp_path)
        replica = _replica(tmp_path)
        primary.insert_batch([_ev(k) for k in range(120)], APP)
        primary.seal(APP)
        primary.insert_batch([_ev(k, u=f"t{k}") for k in range(8)], APP)
        sh = _shipper(primary, replica)
        sh.pass_once()
        assert replica.latest_revision(APP) == primary.latest_revision(APP)
        assert _revs(replica) == _revs(primary)
        assert (
            replica.data_signature(APP) == primary.data_signature(APP)
        )
        lag = replica.replication_lag(APP)
        assert lag["lag"] == 0 and lag["role"] == "replica"
        # a second pass is a no-op, not a re-apply
        before = _revs(replica)
        sh.pass_once()
        assert _revs(replica) == before

    def test_duplicate_frame_is_idempotent(self, tmp_path):
        primary = _primary(tmp_path)
        replica = _replica(tmp_path)
        primary.insert_batch([_ev(k) for k in range(6)], APP)
        t = primary.ship_tail_after(APP, None, 0, 100)
        frame = (APP, None, 1, 0, list(t["revs"]),
                 json.loads(json.dumps(t["rows"], default=str)), t["head"])
        r1 = replica.replication_apply_wal(*frame)
        r2 = replica.replication_apply_wal(*frame)  # retried RPC
        assert r1["watermark"] == r2["watermark"] == 6
        assert _revs(replica) == [1, 2, 3, 4, 5, 6]

    def test_gap_frame_answers_watermark_and_applies_nothing(
        self, tmp_path
    ):
        replica = _replica(tmp_path)
        resp = replica.replication_apply_wal(
            APP, None, 1, 5, [6, 7], [["x"] * 11, ["y"] * 11], 7
        )
        assert resp == {"gap": True, "watermark": 0, "epoch": 1}
        assert replica.latest_revision(APP) == 0

    def test_torn_wal_frame_at_epoch_boundary(self, tmp_path):
        """Satellite: a frame torn mid-ship at an epoch bump. The
        follower's WAL carries a torn line (crash mid-fsync), recovery
        skips it, and the resumed stream — now at the NEW epoch —
        neither skips nor duplicates a revision."""
        primary = _primary(tmp_path)
        replica = _replica(tmp_path)
        primary.insert_batch([_ev(k) for k in range(10)], APP)
        sh1 = _shipper(primary, replica, epoch=1)
        sh1.pass_once()
        assert replica.latest_revision(APP) == 10
        # the primary keeps writing; the ship of revs 11..16 tears:
        # the follower crashed mid-append, leaving a torn WAL line
        primary.insert_batch([_ev(k, u=f"p{k}") for k in range(6)], APP)
        wal = sorted(glob.glob(
            os.path.join(replica.base, f"app_{APP}", "wal-*.jsonl")
        ))[-1]
        with open(wal, "a") as f:
            f.write('[11,[["torn-row-never-com')  # no newline, no close
        replica.close()
        replica2 = ReplicaEventStore(_store_cfg(tmp_path, "replica"))
        # recovery skipped the torn record: watermark is still 10
        assert replica2.latest_revision(APP) == 10
        # failover happened meanwhile: the resumed stream runs at epoch 2
        sh2 = _shipper(primary, replica2, epoch=2)
        sh2.pass_once()
        assert replica2.epoch == 2
        assert _revs(replica2) == list(range(1, 17))  # no skip, no dup
        assert _revs(replica2) == _revs(primary)

    def test_out_of_order_frame_after_gap_backfills(self, tmp_path):
        """A gap answer makes the shipper backfill from the follower's
        watermark — delivered through the commit-hook path itself."""
        primary = _primary(tmp_path)
        replica = _replica(tmp_path)
        sh = _shipper(primary, replica, min_acks=1)
        primary.set_commit_hook(sh._commit_hook)
        # first batch reaches the follower through the hook
        primary.insert_batch([_ev(k) for k in range(3)], APP)
        assert replica.latest_revision(APP) == 3
        # follower loses its state (fresh directory = lost frames)
        sh.links[0].replica = _replica(
            tmp_path, PATH=str(tmp_path / "replica-b")
        )
        primary.insert_batch([_ev(k, u=f"b{k}") for k in range(3)], APP)
        # the gap response triggered a backfill from watermark 0
        assert _revs(sh.links[0].replica) == [1, 2, 3, 4, 5, 6]

    def test_min_acks_failure_raises_but_keeps_rows_durable(
        self, tmp_path
    ):
        primary = _primary(tmp_path)
        replica = _replica(tmp_path)
        sh = _shipper(primary, replica, min_acks=1)

        class _DownLink:
            name = "down:0"

            def call(self, *a, **k):
                raise OSError("connection refused")

        sh.links = [_DownLink()]
        primary.set_commit_hook(sh._commit_hook)
        with pytest.raises(StorageError, match="ack floor"):
            primary.insert_batch([_ev(1)], APP)
        # the rows are durable locally and re-ship once the follower is
        # back — the documented failure contract
        assert primary.latest_revision(APP) == 1
        sh.links = [_DirectLink(replica)]
        sh.pass_once()
        assert _revs(replica) == [1]


# ---------------------------------------------------------------------------
# segment shipping
# ---------------------------------------------------------------------------


class TestSegmentShip:
    def test_ship_resumes_after_follower_restart(self, tmp_path):
        """Satellite: staged files survive a follower restart (the
        `repl-` staging dir is NOT seal garbage) and the resumed ship
        skips them instead of re-sending."""
        primary = _primary(tmp_path)
        replica = _replica(tmp_path)
        primary.insert_batch([_ev(k) for k in range(50)], APP)
        primary.seal(APP)
        name = list(primary.ship_state(APP, None)["segments"])[0]
        seg_path = primary.ship_segment_path(APP, None, name)
        fnames = sorted(
            n for n in os.listdir(seg_path) if not n.startswith(".")
        )
        assert len(fnames) > 2
        # ship only the first two files, then "crash" the follower
        import hashlib
        for fname in fnames[:2]:
            with open(os.path.join(seg_path, fname), "rb") as f:
                data = f.read()
            replica.replication_segment_file(
                APP, None, 1, name, fname, data,
                hashlib.sha256(data).hexdigest(),
            )
        replica.close()
        replica2 = ReplicaEventStore(_store_cfg(tmp_path, "replica"))
        man = replica2.replication_segment_manifest(APP, None, name)
        assert sorted(man["staged"]) == fnames[:2]  # staging survived
        sh = _shipper(primary, replica2)
        link = sh.links[0]
        sh._ship_segment(link, APP, None, name)
        # resumed ship sent only the files that were missing
        sent = link.calls.count("replication_segment_file")
        assert sent == len(fnames) - 2
        assert replica2.replication_segment_manifest(
            APP, None, name
        )["published"]
        assert _revs(replica2) == list(range(1, 51))

    def test_commit_rejects_corrupted_staged_file(self, tmp_path):
        primary = _primary(tmp_path)
        replica = _replica(tmp_path)
        primary.insert_batch([_ev(k) for k in range(30)], APP)
        primary.seal(APP)
        name = list(primary.ship_state(APP, None)["segments"])[0]
        sh = _shipper(primary, replica)
        link = sh.links[0]
        sh._ship_segment(link, APP, None, name)
        assert replica.replication_segment_manifest(
            APP, None, name
        )["published"]
        # a second segment, corrupted in staging before commit
        primary.insert_batch([_ev(k, u=f"c{k}") for k in range(30)], APP)
        primary.seal(APP)
        name2 = [
            n for n in primary.ship_state(APP, None)["segments"]
            if n != name
        ][0]
        seg2 = primary.ship_segment_path(APP, None, name2)
        import hashlib
        files = {}
        for fname in sorted(os.listdir(seg2)):
            if fname.startswith("."):
                continue
            with open(os.path.join(seg2, fname), "rb") as f:
                data = f.read()
            files[fname] = hashlib.sha256(data).hexdigest()
            replica.replication_segment_file(
                APP, None, 1, name2, fname, data, files[fname]
            )
        ns_dir = os.path.join(replica.base, f"app_{APP}")
        staged = os.path.join(ns_dir, f"repl-{name2}")
        victim = sorted(
            n for n in os.listdir(staged) if n != "footer.json"
        )[0]
        with open(os.path.join(staged, victim), "r+b") as f:
            f.write(b"\x00garbage\x00")
        with open(os.path.join(seg2, "footer.json")) as f:
            chash = json.load(f)["content_hash"]
        with pytest.raises(StorageError, match="re-ship|hash"):
            replica.replication_commit_segment(
                APP, None, 1, name2, files, chash
            )
        # nothing published; a clean re-ship succeeds
        assert not replica.replication_segment_manifest(
            APP, None, name2
        )["published"]
        sh._ship_segment(link, APP, None, name2)
        assert replica.replication_segment_manifest(
            APP, None, name2
        )["published"]
        assert _revs(replica) == list(range(1, 61))

    def test_replica_survives_restart_with_sealed_and_tail(self, tmp_path):
        primary = _primary(tmp_path)
        replica = _replica(tmp_path)
        primary.insert_batch([_ev(k) for k in range(40)], APP)
        primary.seal(APP)
        primary.insert_batch([_ev(k, u=f"t{k}") for k in range(5)], APP)
        sh = _shipper(primary, replica)
        sh.pass_once()
        assert replica.latest_revision(APP) == 45
        replica.close()
        replica2 = ReplicaEventStore(_store_cfg(tmp_path, "replica"))
        assert _revs(replica2) == list(range(1, 46))

    def test_tombstones_replicate(self, tmp_path):
        primary = _primary(tmp_path)
        replica = _replica(tmp_path)
        primary.insert_batch([_ev(k) for k in range(10)], APP)
        victim = primary.find_since(APP, 0)[3]
        sh = _shipper(primary, replica)
        sh.pass_once()
        primary.delete(victim.event_id, APP)
        sh.pass_once()
        ids = [e.event_id for e in replica.find_since(APP, 0)]
        assert victim.event_id not in ids
        assert len(ids) == 9


# ---------------------------------------------------------------------------
# fencing, promotion, election
# ---------------------------------------------------------------------------


class TestFencingAndPromotion:
    def test_stale_epoch_is_fenced_and_newer_adopted_durably(
        self, tmp_path
    ):
        replica = _replica(tmp_path)
        replica.replication_apply_wal(APP, None, 3, 0, [1], [_row(1)], 1)
        assert replica.epoch == 3
        with pytest.raises(StorageError, match="fenced"):
            replica.replication_apply_wal(
                APP, None, 2, 1, [2], [["x"] * 11], 2
            )
        replica.close()
        replica2 = ReplicaEventStore(_store_cfg(tmp_path, "replica"))
        assert replica2.epoch == 3  # adoption survived restart

    def test_replica_is_read_only_until_promoted(self, tmp_path):
        replica = _replica(tmp_path)
        with pytest.raises(StorageError, match="read-only"):
            replica.insert_batch([_ev(1)], APP)
        with pytest.raises(StorageError, match="read-only"):
            replica.delete_batch(["nope"], APP)
        replica.promote(5)
        replica.insert_batch([_ev(1)], APP)
        assert replica.latest_revision(APP) == 1
        # a promoted store rejects replication frames
        with pytest.raises(StorageError, match="promoted"):
            replica.replication_apply_wal(
                APP, None, 6, 1, [2], [["x"] * 11], 2
            )

    def test_stale_promotion_raises_and_role_survives_restart(
        self, tmp_path
    ):
        replica = _replica(tmp_path)
        replica.replication_apply_wal(APP, None, 4, 0, [1], [_row(1)], 1)
        with pytest.raises(StorageError, match="stale promotion"):
            replica.promote(4)  # a zombie's claim at the observed epoch
        replica.promote(5)
        replica.close()
        replica2 = ReplicaEventStore(_store_cfg(tmp_path, "replica"))
        assert replica2.role == "primary" and replica2.epoch == 5
        replica2.insert_batch([_ev(2)], APP)

    def test_cas_election_first_bid_wins(self):
        from predictionio_tpu.deploy.registry import LifecycleRecordStore
        

        records = LifecycleRecordStore(_mem_storage())
        el_a = CasElection(records, "events-primary")
        el_b = CasElection(records, "events-primary")
        assert el_a.claim("node-a") == 1
        assert el_a.state().leader == "node-a"
        # a bid already landed for generation 2 — the late bidder loses
        records.append(
            "pio_election_bid", "events-primary",
            {"generation": 2, "claim_token": "other", "candidate": "x",
             "bid_at": 0.0},
        )
        assert el_b.claim("node-b") is None
        assert el_b.claim("node-b", generation=3) == 3
        assert el_b.state() == el_a.state()
        assert el_a.state().generation == 3
        assert records.events("pio_election_bid", "events-primary")
        el_a.gc_bids()
        assert not records.events("pio_election_bid", "events-primary")

    def test_elect_and_promote_catch_up_gate(self, tmp_path):
        from predictionio_tpu.deploy.registry import LifecycleRecordStore
        

        primary = _primary(tmp_path)
        ahead = _replica(tmp_path)
        behind = ReplicaEventStore(
            _store_cfg(tmp_path, "replica-behind")
        )
        behind.init_app(APP)
        primary.insert_batch([_ev(k) for k in range(8)], APP)
        _shipper(primary, ahead).pass_once()
        t = primary.ship_tail_after(APP, None, 0, 4)
        behind.replication_apply_wal(
            APP, None, 1, 0, list(t["revs"][:4]),
            json.loads(json.dumps(t["rows"][:4], default=str)), 4,
        )
        assert ahead.latest_revision(APP) == 8
        assert behind.latest_revision(APP) == 4
        records = LifecycleRecordStore(_mem_storage())
        # the lagging follower withdraws: a reachable peer is ahead
        assert elect_and_promote(
            records, behind, "behind", peers=[_DirectLink(ahead)]
        ) is None
        assert behind.role == "replica"
        # the caught-up follower wins and its epoch out-numbers the
        # primary's frame epoch even though no election minted epoch 1
        gen = elect_and_promote(
            records, ahead, "ahead", peers=[_DirectLink(behind)]
        )
        assert gen == 2
        assert ahead.role == "primary" and ahead.epoch == 2
        # the promoted store serves writes immediately
        ahead.insert_batch([_ev(99, u="post-failover")], APP)
        assert ahead.latest_revision(APP) == 9


# ---------------------------------------------------------------------------
# read-side: lag, read-your-writes, consumer routing
# ---------------------------------------------------------------------------


class TestReadSide:
    def test_lag_watermark_and_wait_for_revision(self, tmp_path):
        primary = _primary(tmp_path)
        replica = _replica(tmp_path)
        primary.insert_batch([_ev(k) for k in range(5)], APP)
        t = primary.ship_tail_after(APP, None, 0, 3)
        replica.replication_apply_wal(
            APP, None, 1, 0, list(t["revs"][:3]),
            json.loads(json.dumps(t["rows"][:3], default=str)), 5,
        )
        lag = replica.replication_lag(APP)
        assert lag == {
            "watermark": 3, "head": 5, "lag": 2, "epoch": 1,
            "role": "replica",
        }
        assert replica.wait_for_revision(APP, 3, timeout_s=0.1)
        assert not replica.wait_for_revision(APP, 5, timeout_s=0.1)
        _shipper(primary, replica).pass_once()
        assert replica.wait_for_revision(APP, 5, timeout_s=0.1)
        assert replica.replication_lag(APP)["lag"] == 0

    def test_replica_read_storage_routes_reads_not_writes(self, tmp_path):
        

        control = _mem_storage()
        control.get_events().init_app(APP)
        control.get_events().init_app(APP + 1)
        replica = _replica(tmp_path)
        replica.replication_apply_wal(
            APP, None, 1, 0, [1, 2, 3], [_row(k) for k in range(3)], 3
        )
        view = ReplicaReadStorage(control, replica, [APP])
        ev = view.get_events()
        # replicated app reads hit the replica
        assert [e.revision for e in ev.find_since(APP, 0)] == [1, 2, 3]
        assert ev.latest_revision(APP) == 3
        # writes go to control (the replica would raise read-only)
        ev.insert_batch([_ev(1)], APP + 1)
        assert ev.latest_revision(APP + 1) == 1
        assert control.get_events().latest_revision(APP + 1) == 1
        # non-replicated app reads hit control
        assert [e.revision for e in ev.find_since(APP + 1, 0)] == [1]
        # single revision stream (replica revisions ARE primary
        # revisions), and lifecycle/meta DAOs pass through to control
        assert [k for k, _s, _sh in ev.revision_streams()] == ["0"]
        assert view.get_meta_data_apps() is control.get_meta_data_apps()
        assert ev.replication_lag(APP)["watermark"] == 3


# ---------------------------------------------------------------------------
# real daemon transport
# ---------------------------------------------------------------------------


class TestRemoteTransport:
    def test_ship_over_storage_daemon(self, tmp_path):
        from predictionio_tpu.data.api.storage_server import StorageServer
        from predictionio_tpu.data.storage.registry import (
            SourceConfig,
            Storage,
            StorageConfig,
        )

        follower_storage = Storage(StorageConfig(
            sources={
                "REP": SourceConfig("REP", "segmentfs-replica", {
                    "PATH": str(tmp_path / "replica"),
                    "SEAL_INTERVAL_S": "3600",
                }),
                "M": SourceConfig("M", "memory", {}),
            },
            repositories={
                "METADATA": "M", "EVENTDATA": "REP", "MODELDATA": "M",
            },
        ))
        daemon = StorageServer(
            follower_storage, host="127.0.0.1", port=0
        ).start()
        try:
            replica = follower_storage.get_events()
            assert isinstance(replica, ReplicaEventStore)
            replica.init_app(APP)
            primary = _primary(tmp_path)
            primary.insert_batch([_ev(k) for k in range(60)], APP)
            primary.seal(APP)
            primary.insert_batch(
                [_ev(k, u=f"t{k}") for k in range(4)], APP
            )
            cfg = ReplicationConfig(
                followers=(f"127.0.0.1:{daemon.port}",), timeout_s=10.0
            )
            sh = SegmentShipper(
                primary, cfg, epoch=1, metrics=MetricsRegistry()
            )
            assert isinstance(sh.links[0], FollowerLink)
            sh.pass_once()
            assert _revs(replica) == _revs(primary)
            # the remote client surface consumers use
            from predictionio_tpu.data.storage.remote import (
                RemoteEventStore,
            )

            remote = RemoteEventStore({
                "HOST": "127.0.0.1", "PORT": str(daemon.port),
            })
            lag = remote.replication_lag(APP)
            assert lag["lag"] == 0 and lag["watermark"] == 64
            assert remote.wait_for_revision(APP, 64, timeout_s=1.0)
            status = remote.replication_status()
            assert status["role"] == "replica"
            assert str(APP) in status["namespaces"]
        finally:
            daemon.shutdown()
