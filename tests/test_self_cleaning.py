"""SelfCleaningDataSource tests (port of reference
SelfCleaningDataSourceTest: compaction, dedupe, age-out)."""

import datetime as dt

import pytest

from predictionio_tpu.core.base import RuntimeContext
from predictionio_tpu.core.self_cleaning import (
    EventWindow,
    SelfCleaningDataSource,
    parse_duration,
)
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import App, EventQuery

UTC = dt.timezone.utc


class CleaningSource(SelfCleaningDataSource):
    def __init__(self, app_name, window):
        self.app_name = app_name
        self.event_window = window


@pytest.fixture()
def app(fresh_storage):
    app_id = fresh_storage.get_meta_data_apps().insert(App(id=0, name="clean"))
    fresh_storage.get_events().init_app(app_id)
    return fresh_storage, app_id


def all_events(storage, app_id):
    return list(storage.get_events().find(EventQuery(app_id=app_id)))


def test_parse_duration():
    assert parse_duration("4 days") == dt.timedelta(days=4)
    assert parse_duration("12 hours") == dt.timedelta(hours=12)
    assert parse_duration("1 week") == dt.timedelta(weeks=1)
    with pytest.raises(ValueError):
        parse_duration("fortnight")


def test_compress_properties(app):
    storage, app_id = app
    t0 = dt.datetime(2026, 1, 1, tzinfo=UTC)
    storage.get_events().insert_batch(
        [
            Event(event="$set", entity_type="item", entity_id="i1",
                  properties={"a": 1, "b": 2}, event_time=t0),
            Event(event="$set", entity_type="item", entity_id="i1",
                  properties={"a": 9}, event_time=t0 + dt.timedelta(days=1)),
            Event(event="$unset", entity_type="item", entity_id="i1",
                  properties={"b": None},
                  event_time=t0 + dt.timedelta(days=2)),
            Event(event="$set", entity_type="item", entity_id="i2",
                  properties={"x": 1}, event_time=t0),
        ],
        app_id,
    )
    src = CleaningSource("clean", EventWindow(compress_properties=True))
    stats = src.clean_persisted_events(RuntimeContext(storage=storage))
    assert stats["compacted"] == 3  # i1's three events; i2 untouched

    events = all_events(storage, app_id)
    i1 = [e for e in events if e.entity_id == "i1"]
    assert len(i1) == 1
    assert i1[0].event == "$set"
    assert i1[0].properties.to_dict() == {"a": 9}  # b unset, a overwritten
    assert len([e for e in events if e.entity_id == "i2"]) == 1


def test_compact_fully_deleted_entity(app):
    storage, app_id = app
    t0 = dt.datetime(2026, 1, 1, tzinfo=UTC)
    storage.get_events().insert_batch(
        [
            Event(event="$set", entity_type="item", entity_id="gone",
                  properties={"a": 1}, event_time=t0),
            Event(event="$delete", entity_type="item", entity_id="gone",
                  event_time=t0 + dt.timedelta(days=1)),
        ],
        app_id,
    )
    src = CleaningSource("clean", EventWindow(compress_properties=True))
    src.clean_persisted_events(RuntimeContext(storage=storage))
    assert all_events(storage, app_id) == []  # deleted entity leaves nothing


def test_remove_duplicates(app):
    storage, app_id = app
    t0 = dt.datetime(2026, 1, 1, tzinfo=UTC)
    dup = dict(
        event="buy", entity_type="user", entity_id="u1",
        target_entity_type="item", target_entity_id="i1",
    )
    storage.get_events().insert_batch(
        [
            Event(**dup, event_time=t0),
            Event(**dup, event_time=t0 + dt.timedelta(hours=1)),
            Event(**dup, event_time=t0 + dt.timedelta(hours=2)),
            Event(event="buy", entity_type="user", entity_id="u2",
                  target_entity_type="item", target_entity_id="i1",
                  event_time=t0),
        ],
        app_id,
    )
    src = CleaningSource("clean", EventWindow(remove_duplicates=True))
    stats = src.clean_persisted_events(RuntimeContext(storage=storage))
    assert stats["deduplicated"] == 2
    events = all_events(storage, app_id)
    assert len(events) == 2
    # the EARLIEST copy survives
    u1 = [e for e in events if e.entity_id == "u1"]
    assert u1[0].event_time == t0


def test_remove_duplicates_with_list_valued_properties(app):
    """ADVICE r1: list/dict-valued properties must not crash the dedupe
    key (canonical-JSON key, not a tuple of raw values)."""
    storage, app_id = app
    t0 = dt.datetime(2026, 1, 1, tzinfo=UTC)
    base = dict(
        event="view", entity_type="user", entity_id="u1",
        target_entity_type="item", target_entity_id="i1",
        properties={"categories": ["a", "b"], "meta": {"k": 1}},
    )
    storage.get_events().insert_batch(
        [Event(**base, event_time=t0),
         Event(**base, event_time=t0 + dt.timedelta(hours=1))],
        app_id,
    )
    src = CleaningSource("clean", EventWindow(remove_duplicates=True))
    stats = src.clean_persisted_events(RuntimeContext(storage=storage))
    assert stats["deduplicated"] == 1
    assert len(all_events(storage, app_id)) == 1


def test_age_out(app):
    storage, app_id = app
    now = dt.datetime.now(UTC)
    storage.get_events().insert_batch(
        [
            Event(event="view", entity_type="user", entity_id="old",
                  target_entity_type="item", target_entity_id="i1",
                  event_time=now - dt.timedelta(days=30)),
            Event(event="view", entity_type="user", entity_id="new",
                  target_entity_type="item", target_entity_id="i1",
                  event_time=now - dt.timedelta(hours=1)),
            # $set events are NOT aged out (they carry state, not history)
            Event(event="$set", entity_type="item", entity_id="i1",
                  properties={"a": 1},
                  event_time=now - dt.timedelta(days=60)),
        ],
        app_id,
    )
    src = CleaningSource("clean", EventWindow(duration="7 days"))
    stats = src.clean_persisted_events(RuntimeContext(storage=storage))
    assert stats["aged_out"] == 1
    remaining = all_events(storage, app_id)
    ids = {e.entity_id for e in remaining}
    assert ids == {"new", "i1"}


def test_no_window_is_noop(app):
    storage, app_id = app
    storage.get_events().insert(
        Event(event="view", entity_type="user", entity_id="u",
              target_entity_type="item", target_entity_id="i"),
        app_id,
    )
    src = CleaningSource("clean", None)
    stats = src.clean_persisted_events(RuntimeContext(storage=storage))
    assert stats == {"compacted": 0, "deduplicated": 0, "aged_out": 0}
    assert len(all_events(storage, app_id)) == 1
