"""Partitioned training reads (VERDICT r3 #8).

EventQuery.shard=(i, n) splits a training read into n disjoint,
complete, entity-local partitions — the reference's parallel HBase
region scans feeding executor-partitioned RDDs (HBPEvents.scala:84-90).
Unit layer: shard semantics across backends + the wire. Integration:
TWO jax.distributed processes each stream only their shard from one
storage daemon, reassemble via parallel/loader.allgather_rows, and the
mesh-trained factors match a single-process full-read train.
"""

import datetime as dt
import os
import socket
import subprocess
import sys
import textwrap
from pathlib import Path

import numpy as np

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import wire
from predictionio_tpu.data.storage.base import App, EventQuery, shard_of
from predictionio_tpu.data.storage.registry import (
    SourceConfig,
    Storage,
    StorageConfig,
)

from test_remote_storage import (  # noqa: F401  (daemon is a fixture)
    _remote_env,
    daemon,
)

REPO = Path(__file__).resolve().parent.parent

N_USERS, N_ITEMS, N_EDGES, RANK, ITERS = 48, 24, 1200, 8, 3


def _storage(kind, tmp_path):
    tmp_path.mkdir(parents=True, exist_ok=True)
    if kind == "memory":
        src = SourceConfig("S", "memory", {})
    elif kind == "sqlite":
        src = SourceConfig("S", "sqlite", {"PATH": str(tmp_path / "s.db")})
    else:
        src = SourceConfig("S", "parquetfs", {"PATH": str(tmp_path / "pq")})
    cfg = StorageConfig(
        sources={"S": src, "M": SourceConfig("M", "memory", {})},
        repositories={"METADATA": "M", "EVENTDATA": "S", "MODELDATA": "M"},
    )
    return Storage(cfg)


def _seed(storage, n=60):
    app_id = storage.get_meta_data_apps().insert(App(0, "shardapp"))
    ev = storage.get_events()
    ev.init_app(app_id)
    t0 = dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)
    ev.insert_batch(
        [
            Event(
                event="rate", entity_type="user", entity_id=f"u{i % 17}",
                target_entity_type="item", target_entity_id=f"i{i % 5}",
                properties={"rating": float(i % 5 + 1)}, event_time=t0,
            )
            for i in range(n)
        ],
        app_id,
    )
    return app_id


class TestShardSemantics:
    def test_disjoint_and_complete(self, tmp_path):
        """Across backends: the n shards of a find partition the result
        set exactly, and each entity's events land in ONE shard."""
        for kind in ("memory", "sqlite", "parquetfs"):
            storage = _storage(kind, tmp_path / kind)
            app_id = _seed(storage)
            store = storage.get_events()
            full = {
                e.event_id for e in store.find(EventQuery(app_id=app_id))
            }
            n_shards = 3
            seen: dict[str, int] = {}
            union = set()
            for s in range(n_shards):
                part = list(
                    store.find(
                        EventQuery(app_id=app_id, shard=(s, n_shards))
                    )
                )
                for e in part:
                    assert e.event_id not in union, f"{kind}: overlap"
                    union.add(e.event_id)
                    assert shard_of(e.entity_id, n_shards) == s
                    prev = seen.setdefault(e.entity_id, s)
                    assert prev == s, f"{kind}: entity split across shards"
            assert union == full, f"{kind}: shards do not cover find()"

    def test_find_frame_sharded(self, tmp_path):
        for kind in ("memory", "sqlite", "parquetfs"):
            storage = _storage(kind, tmp_path / ("f" + kind))
            app_id = _seed(storage)
            store = storage.get_events()
            q = EventQuery(app_id=app_id)
            fast = getattr(store, "find_frame", None)

            def frame(query):
                from predictionio_tpu.data.store.columnar import EventFrame

                if fast is not None:
                    return fast(query)
                return EventFrame.from_events(store.find(query))

            total = len(frame(q).entity_idx)
            got = sum(
                len(
                    frame(
                        EventQuery(app_id=app_id, shard=(s, 4))
                    ).entity_idx
                )
                for s in range(4)
            )
            assert got == total, kind

    def test_wire_round_trip(self):
        q = EventQuery(app_id=3, shard=(2, 5))
        q2 = wire.decode(wire.encode(q))
        assert q2.shard == (2, 5)
        assert wire.decode(wire.encode(EventQuery(app_id=3))).shard is None


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


_CHILD = textwrap.dedent(
    """
    import sys
    from predictionio_tpu.utils.cpuonly import force_cpu_platform
    force_cpu_platform(n_devices=4)
    import jax

    coordinator, pid, app_id, out_path = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
    )
    jax.distributed.initialize(
        coordinator_address=coordinator, num_processes=2, process_id=pid
    )
    assert len(jax.devices()) == 8

    import numpy as np
    from predictionio_tpu.data.storage.base import EventQuery
    from predictionio_tpu.data.storage.registry import Storage
    from predictionio_tpu.models import als
    from predictionio_tpu.parallel.loader import allgather_rows
    from predictionio_tpu.parallel.mesh import make_mesh

    # THE partitioned read: this process streams ONLY its entity-hash
    # shard from the daemon (server-side filter, wire traffic / 2)
    store = Storage().get_events()
    rows, cols, vals = [], [], []
    for e in store.find(EventQuery(app_id=app_id, shard=(pid, 2))):
        rows.append(int(e.entity_id[1:]))
        cols.append(int(e.target_entity_id[1:]))
        vals.append(float(e.properties.get("rating")))
    local = (
        np.asarray(rows, np.int32),
        np.asarray(cols, np.int32),
        np.asarray(vals, np.float32),
    )
    print("SHARD-ROWS", pid, len(rows))
    rows, cols, vals = allgather_rows(*local)

    mesh = make_mesh()  # 8 devices spanning both processes
    m = als.train(
        rows, cols, vals, {n_users}, {n_items},
        als.ALSParams(rank={rank}, iterations={iters}, implicit_prefs=True),
        mesh=mesh,
    )
    if pid == 0:
        np.savez(out_path, uf=m.user_factors, itf=m.item_factors,
                 n_local=len(local[0]))
    print("CHILD-OK", pid)
    """
)


def test_two_process_partitioned_read_train(daemon, tmp_path):  # noqa: F811
    """End-to-end HBPEvents role: daemon-sharded reads → allgather →
    mesh-sharded windowed ALS, equal to a full-read train."""
    env = _remote_env(tmp_path, daemon)

    # seed the daemon with a training set through the remote backend
    seed_env = {k: v for k, v in env.items()}
    seed = subprocess.run(
        [
            sys.executable, "-c",
            textwrap.dedent(
                f"""
                import datetime as dt
                import numpy as np
                from predictionio_tpu.data.event import Event
                from predictionio_tpu.data.storage.base import App
                from predictionio_tpu.data.storage.registry import Storage

                rng = np.random.RandomState(7)
                rows = rng.randint(0, {N_USERS}, {N_EDGES})
                cols = rng.randint(0, {N_ITEMS}, {N_EDGES})
                vals = rng.randint(1, 6, {N_EDGES})
                s = Storage()
                app_id = s.get_meta_data_apps().insert(App(0, "partapp"))
                ev = s.get_events()
                ev.init_app(app_id)
                t0 = dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)
                ev.insert_batch(
                    [
                        Event(event="rate", entity_type="user",
                              entity_id=f"u{{r}}", target_entity_type="item",
                              target_entity_id=f"i{{c}}",
                              properties={{"rating": float(v)}},
                              event_time=t0)
                        for r, c, v in zip(rows, cols, vals)
                    ],
                    app_id,
                )
                print(app_id)
                """
            ),
        ],
        env=seed_env, cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert seed.returncode == 0, seed.stderr[-3000:]
    app_id = int(seed.stdout.strip().splitlines()[-1])

    port = _free_port()
    out_path = tmp_path / "factors.npz"
    child = (
        _CHILD.replace("{n_users}", str(N_USERS))
        .replace("{n_items}", str(N_ITEMS))
        .replace("{rank}", str(RANK))
        .replace("{iters}", str(ITERS))
    )
    procs = [
        subprocess.Popen(
            [
                sys.executable, "-c", child,
                f"127.0.0.1:{port}", str(pid), str(app_id), str(out_path),
            ],
            env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for pid in (0, 1)
    ]
    outs = [p.communicate(timeout=300) for p in procs]
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, f"child failed:\n{out}\n{err[-3000:]}"
        assert "CHILD-OK" in out

    # each process really read a PARTIAL stream
    shard_counts = {}
    for out, _err in outs:
        for line in out.splitlines():
            if line.startswith("SHARD-ROWS"):
                _tag, pid, n = line.split()
                shard_counts[int(pid)] = int(n)
    assert set(shard_counts) == {0, 1}
    assert shard_counts[0] + shard_counts[1] == N_EDGES
    assert 0 < shard_counts[0] < N_EDGES

    with np.load(out_path) as z:
        uf2, itf2 = z["uf"], z["itf"]

    # single-process full-read reference over the same mesh shape.
    # Edge ORDER differs (shard-0 rows then shard-1 rows vs insertion
    # order), and ALS is order-invariant only up to f32 reduction
    # noise, so compare against a train on the same gathered order.
    from predictionio_tpu.models import als
    from predictionio_tpu.parallel.mesh import make_mesh

    remote_cfg = StorageConfig(
        sources={
            "RMT": SourceConfig(
                "RMT", "remote",
                {"HOST": "127.0.0.1", "PORT": str(daemon)},
            )
        },
        repositories={
            "METADATA": "RMT", "EVENTDATA": "RMT", "MODELDATA": "RMT",
        },
    )
    store = Storage(remote_cfg).get_events()
    parts = []
    for s in range(2):
        r, c, v = [], [], []
        for e in store.find(EventQuery(app_id=app_id, shard=(s, 2))):
            r.append(int(e.entity_id[1:]))
            c.append(int(e.target_entity_id[1:]))
            v.append(float(e.properties.get("rating")))
        parts.append(
            (np.asarray(r, np.int32), np.asarray(c, np.int32),
             np.asarray(v, np.float32))
        )
    rows = np.concatenate([p[0] for p in parts])
    cols = np.concatenate([p[1] for p in parts])
    vals = np.concatenate([p[2] for p in parts])
    ref = als.train(
        rows, cols, vals, N_USERS, N_ITEMS,
        als.ALSParams(rank=RANK, iterations=ITERS, implicit_prefs=True),
        mesh=make_mesh(),
    )
    # cross-process collective reduction order differs from the
    # single-process schedule by f32 noise (observed ≤3e-5 absolute)
    np.testing.assert_allclose(uf2, ref.user_factors, rtol=2e-3, atol=1e-4)
    np.testing.assert_allclose(itf2, ref.item_factors, rtol=2e-3, atol=1e-4)
