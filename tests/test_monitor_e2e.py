"""Monitoring plane end-to-end (ISSUE 8 acceptance): a live query
server with the TSDB sampler + SLO engine at test-speed knobs; an
injected PR-4 fault on `dispatch.device` drives the availability SLO
to `firing` within two evaluation intervals and back to `resolved`
after the fault clears — asserted via GET /alerts. Also covers
/debug/tsdb over live traffic and the trace-the-next-N-batches
capture round trip."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage.base import App
from predictionio_tpu.obs.monitor import SLOSpec, get_monitor
from predictionio_tpu.resilience import faults
from predictionio_tpu.workflow.core import run_train
from predictionio_tpu.workflow.server import (
    QueryServer,
    QueryServerConfig,
    build_runtime,
)

VARIANT = {
    "id": "mon",
    "engineFactory":
        "predictionio_tpu.engines.recommendation.RecommendationEngine",
    "datasource": {"params": {"app_name": "monapp"}},
    "algorithms": [
        {"name": "als", "params": {"rank": 8, "num_iterations": 3}}
    ],
}

# test-speed SLO: tiny windows, burn threshold 1.0, one-interval
# promotion and resolution — "firing within two evaluation intervals"
EVAL_S = 0.4
SAMPLE_S = 0.2
SPEC = SLOSpec(
    name="queries-avail",
    kind="availability",
    objective=0.99,
    server="query",
    route="/queries.json",
    fast_window_s=3.0,
    window_s=6.0,
    burn_threshold=1.0,
    min_samples=3,
    for_s=0.0,
    resolve_s=0.0,
)


def _seed(storage, n_users=8, seed=0):
    apps = storage.get_meta_data_apps()
    app_id = apps.insert(App(id=0, name="monapp"))
    events = storage.get_events()
    events.init_app(app_id)
    rng = np.random.RandomState(seed)
    batch = []
    for u in range(n_users):
        for _ in range(15):
            i = rng.randint(0, 5) + (u % 2) * 5
            batch.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties={"rating": 5.0},
            ))
    events.insert_batch(batch, app_id)
    return app_id


def _post(port, path, body, timeout=20):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode() or "null")


def _get(port, path):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=20
        ) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode() or "null")


def _alert_state(port, name):
    _status, payload = _get(port, "/alerts")
    row = next((r for r in payload["slos"] if r["slo"] == name), None)
    return None if row is None else row["state"]


class _Traffic:
    """Background query stream so the sampler always has fresh counter
    ticks — burn rates need traffic to judge (and to resolve)."""

    def __init__(self, port):
        self.port = port
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        i = 0
        while not self._stop.is_set():
            i += 1
            try:
                _post(
                    self.port, "/queries.json",
                    {"user": f"u{i % 8}", "num": 3},
                )
            except Exception:
                pass
            time.sleep(0.02)

    def __enter__(self):
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._thread.join(timeout=5)


@pytest.fixture()
def monitored_server(fresh_storage):
    monitor = get_monitor()
    saved = (monitor.sampler_interval_s, monitor.slo_interval_s)
    monitor.sampler_interval_s = SAMPLE_S
    monitor.slo_interval_s = EVAL_S
    monitor.set_slos([SPEC])
    _seed(fresh_storage)
    inst = run_train(fresh_storage, VARIANT)
    srv = QueryServer(
        fresh_storage, build_runtime(fresh_storage, inst),
        QueryServerConfig(ip="127.0.0.1", port=0, batch_window_ms=1.0),
    )
    port = srv.start()
    yield srv, port
    faults.clear()
    srv.stop()
    monitor.set_slos([])
    monitor.sampler_interval_s, monitor.slo_interval_s = saved


def _wait_for_state(port, want, timeout_s):
    deadline = time.monotonic() + timeout_s
    state = None
    while time.monotonic() < deadline:
        state = _alert_state(port, SPEC.name)
        if state == want:
            return state
        time.sleep(0.1)
    return state


def test_injected_fault_fires_and_resolves_the_availability_slo(
    monitored_server,
):
    srv, port = monitored_server
    with _Traffic(port):
        # healthy baseline: traffic flows, alert stays quiet
        assert _wait_for_state(port, "inactive", 2.0) == "inactive"
        # inject the PR-4 fault on the query server's device dispatch.
        # The @live scope fails the per-query fallback too (the
        # scope-less spec deliberately keeps the fallback alive), so
        # every routed query 500s — the availability SLO's input.
        faults.install(faults.parse_spec("dispatch.device@live:error:1"))
        t_fault = time.monotonic()
        state = _wait_for_state(port, "firing", 15.0)
        t_firing = time.monotonic() - t_fault
        assert state == "firing", f"alert stuck in {state!r}"
        # acceptance bar: firing within two evaluation intervals of the
        # breach being visible (sampler tick + window fill allowed for)
        assert t_firing < SPEC.fast_window_s + 4 * EVAL_S + 2 * SAMPLE_S
        # the gauge agrees with /alerts
        _s, payload = _get(port, "/alerts")
        assert SPEC.name in payload["firing"]
        # clear the fault: traffic heals, errors age out of both
        # windows, and the alert resolves
        faults.clear()
        state = _wait_for_state(
            port, "resolved", SPEC.window_s + 10.0
        )
        assert state == "resolved", f"alert stuck in {state!r}"


def test_debug_tsdb_serves_live_history(monitored_server):
    srv, port = monitored_server
    for i in range(6):
        status, _ = _post(
            port, "/queries.json", {"user": f"u{i % 8}", "num": 3}
        )
        assert status == 200
    # let the sampler tick at least twice
    time.sleep(2.5 * SAMPLE_S)
    status, listing = _get(port, "/debug/tsdb")
    assert status == 200 and listing["enabled"]
    names = {s["name"] for s in listing["series"]}
    assert "http_requests_total" in names
    assert "serve_seconds_count" in names
    status, series = _get(
        port,
        "/debug/tsdb?name=http_requests_total"
        "&labels=server:query,path:/queries.json,status:200",
    )
    assert status == 200
    pts = series["series"][0]["points"]
    assert pts and pts[-1][1] >= 6
    status, agg = _get(
        port,
        "/debug/tsdb?name=http_requests_total&agg=increase&window_s=60",
    )
    assert status == 200 and agg["value"] >= 6


def test_trace_capture_forces_batch_retention(monitored_server):
    from predictionio_tpu.obs.spans import get_default_recorder

    srv, port = monitored_server
    recorder = get_default_recorder()
    saved_rate = recorder.sample_rate
    recorder.sample_rate = 0.0  # nothing survives without the capture
    try:
        status, armed = _post(port, "/debug/traces/capture", {"n": 3})
        assert status == 200
        cap = armed["capture"]
        for i in range(6):
            _post(port, "/queries.json", {"user": f"u{i % 8}", "num": 3})
        deadline = time.monotonic() + 10
        result = None
        while time.monotonic() < deadline:
            status, result = _get(port, f"/debug/traces?capture={cap}")
            assert status == 200
            if result["done"] and result["traces"]:
                break
            time.sleep(0.1)
        assert result["done"], "capture credits never consumed"
        assert result["traces"], "captured batches retained no traces"
        assert all(
            t["kept"].startswith("capture") for t in result["traces"]
        )
        # bad capture ids 404; invalid n 400
        status, _ = _get(port, "/debug/traces?capture=nope")
        assert status == 404
        status, _ = _post(port, "/debug/traces/capture", {"n": 0})
        assert status == 400
    finally:
        recorder.sample_rate = saved_rate


def test_alerts_payload_without_engine_is_stable(fresh_storage):
    """The /alerts surface must answer sanely with no SLOs configured
    (the default deployment)."""
    monitor = get_monitor()
    monitor.set_slos([])
    _seed(fresh_storage, n_users=2)
    inst = run_train(fresh_storage, VARIANT)
    srv = QueryServer(
        fresh_storage, build_runtime(fresh_storage, inst),
        QueryServerConfig(ip="127.0.0.1", port=0),
    )
    port = srv.start()
    try:
        status, payload = _get(port, "/alerts")
        assert status == 200
        assert payload["alerts"] == [] and payload["firing"] == []
    finally:
        srv.stop()
