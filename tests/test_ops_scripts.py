"""bin/pio-start-all / pio-stop-all / pio-daemon (VERDICT r1 #8, reference
bin/pio-start-all, bin/pio-daemon): single-command bring-up of storage
server + event server + admin + dashboard, pidfile lifecycle, clean stop."""

import json
import os
import socket
import subprocess
import time
import urllib.request
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
BIN = REPO / "bin"


def free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def wait_http(url, timeout=30):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=2) as r:
                return r.status, r.read().decode()
        except OSError:
            time.sleep(0.2)
    raise TimeoutError(url)


def test_start_all_and_stop_all(tmp_path):
    env = dict(os.environ)
    ports = {
        "PIO_STORAGE_SERVER_PORT": str(free_port()),
        "PIO_EVENTSERVER_PORT": str(free_port()),
        "PIO_ADMINSERVER_PORT": str(free_port()),
        "PIO_DASHBOARD_PORT": str(free_port()),
    }
    env.update(ports)
    env["PIO_FS_BASEDIR"] = str(tmp_path / "store")
    run_dir = tmp_path / "run"
    env["PIO_RUN_DIR"] = str(run_dir)
    env["PIO_LOG_DIR"] = str(tmp_path / "log")

    out = subprocess.run(
        [str(BIN / "pio-start-all")], env=env, capture_output=True,
        text=True, timeout=60,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    try:
        # all four services answer
        status, body = wait_http(
            f"http://127.0.0.1:{ports['PIO_STORAGE_SERVER_PORT']}/health"
        )
        assert status == 200 and json.loads(body)["status"] == "alive"
        wait_http(
            f"http://127.0.0.1:{ports['PIO_EVENTSERVER_PORT']}/"
        )
        wait_http(
            f"http://127.0.0.1:{ports['PIO_ADMINSERVER_PORT']}/"
        )
        wait_http(
            f"http://127.0.0.1:{ports['PIO_DASHBOARD_PORT']}/"
        )
        pids = {
            p.name: int(p.read_text()) for p in run_dir.glob("pio-*.pid")
        }
        assert len(pids) == 4, pids
        # double-start refuses while running
        again = subprocess.run(
            [str(BIN / "pio-start-all")], env=env, capture_output=True,
            text=True, timeout=60,
        )
        assert again.returncode != 0
        assert "already running" in again.stdout + again.stderr
    finally:
        stop = subprocess.run(
            [str(BIN / "pio-stop-all")], env=env, capture_output=True,
            text=True, timeout=60,
        )
    assert stop.returncode == 0, stop.stdout + stop.stderr
    assert stop.stdout.count("stopped") == 4, stop.stdout
    assert not list(run_dir.glob("pio-*.pid"))
    for pid in pids.values():
        with pytest.raises(ProcessLookupError):
            os.kill(pid, 0)
    # idempotent stop
    stop2 = subprocess.run(
        [str(BIN / "pio-stop-all")], env=env, capture_output=True,
        text=True, timeout=60,
    )
    assert stop2.returncode == 0
    assert "nothing to stop" in stop2.stdout
