"""segmentfs (ISSUE 13): the columnar LSM event backend — seal/compact
lifecycle, WAL crash recovery, exactly-once revision tails across seal
and compaction, bit-identical find_frame parity, the target-entity
posting read, the SegmentStager device path, DataView delegation, and
the sharded batch-req-id routing satellite."""

import datetime as dt
import threading

import numpy as np
import pytest

from predictionio_tpu.data import DataMap, Event
from predictionio_tpu.data.storage.base import EventQuery, StorageError
from predictionio_tpu.data.storage.segmentfs import SegmentFSEventStore
from predictionio_tpu.data.store.columnar import EventFrame

UTC = dt.timezone.utc
APP = 1


def T(i):
    return dt.datetime(2024, 1, 1, tzinfo=UTC) + dt.timedelta(hours=i)


def ev(name, eid, t=0, etype="user", **kw):
    return Event(
        event=name, entity_type=etype, entity_id=eid, event_time=T(t), **kw
    )


def rate(u, i, r, t=0):
    return ev(
        "rate", u, t=t, target_entity_type="item", target_entity_id=i,
        properties=DataMap({"rating": float(r)}),
    )


@pytest.fixture
def store(tmp_path):
    s = SegmentFSEventStore(
        {"PATH": str(tmp_path / "seg"), "SEAL_INTERVAL_S": "3600"}
    )
    s.init_app(APP)
    yield s
    s.close()


def frames_equal(a: EventFrame, b: EventFrame):
    np.testing.assert_array_equal(a.event_code, b.event_code)
    np.testing.assert_array_equal(a.entity_idx, b.entity_idx)
    np.testing.assert_array_equal(a.target_idx, b.target_idx)
    np.testing.assert_array_equal(a.time_ms, b.time_ms)
    np.testing.assert_array_equal(a.value, b.value)
    assert a.event_vocab.to_dict() == b.event_vocab.to_dict()
    assert a.entity_vocab.to_dict() == b.entity_vocab.to_dict()
    assert a.target_vocab.to_dict() == b.target_vocab.to_dict()
    assert a.entity_type == b.entity_type
    assert a.target_entity_type == b.target_entity_type


# ---------------------------------------------------------------------------
# Sealed-state contract (the shared suite runs against the unsealed tail)
# ---------------------------------------------------------------------------


class TestSealedContract:
    def test_contract_behaviors_survive_seal(self, store):
        store.insert_batch(
            [rate(f"u{i % 3}", f"i{i % 2}", i + 1, t=i) for i in range(8)],
            APP,
        )
        store.insert(ev("$set", "u0", t=9, properties=DataMap({"a": 1})), APP)
        assert store.seal(APP) == 9
        # time order + filters
        found = list(store.find(EventQuery(app_id=APP, event_names=["rate"])))
        assert len(found) == 8
        times = [e.event_time for e in found]
        assert times == sorted(times)
        # entity-scoped read (bloom + vocab gate)
        u0 = list(
            store.find(EventQuery(app_id=APP, entity_id="u0"))
        )
        assert {e.entity_id for e in u0} == {"u0"}
        # aggregation folds the sealed $set
        agg = store.aggregate_properties(APP, "user")
        assert agg["u0"].to_dict() == {"a": 1}
        # get + delete straight out of a sealed segment
        eid = found[0].event_id
        assert store.get(eid, APP).event == "rate"
        assert store.delete(eid, APP)
        assert store.get(eid, APP) is None
        assert len(list(store.find(EventQuery(app_id=APP)))) == 8

    def test_overwrite_sealed_row(self, store):
        ids = store.insert_batch([rate("u1", "i1", 5), rate("u2", "i2", 4)], APP)
        store.seal(APP)
        store.insert(rate("u1", "i9", 3, t=5).with_id(ids[0]), APP)
        got = store.get(ids[0], APP)
        assert got.target_entity_id == "i9"
        # the superseded sealed row is masked: one live copy of the id
        all_ids = [e.event_id for e in store.find(EventQuery(app_id=APP))]
        assert all_ids.count(ids[0]) == 1
        # and the revision advanced (overwrite = new revision)
        assert got.revision == 3

    def test_channel_isolation_sealed(self, store):
        store.init_app(APP, 7)
        store.insert(rate("u1", "i1", 5), APP)
        store.insert(rate("u2", "i2", 4), APP, 7)
        store.seal(APP)
        store.seal(APP, 7)
        assert [
            e.entity_id for e in store.find(EventQuery(app_id=APP))
        ] == ["u1"]
        assert [
            e.entity_id
            for e in store.find(EventQuery(app_id=APP, channel_id=7))
        ] == ["u2"]


# ---------------------------------------------------------------------------
# WAL crash recovery + revision durability
# ---------------------------------------------------------------------------


class TestCrashRecovery:
    def test_unsealed_tail_survives_crash(self, tmp_path):
        path = str(tmp_path / "seg")
        s1 = SegmentFSEventStore({"PATH": path, "SEAL_INTERVAL_S": "3600"})
        s1.init_app(APP)
        ids = s1.insert_batch([rate(f"u{i}", "i1", i + 1) for i in range(5)], APP)
        # no close(): the process dies here; the fsync'd WAL is the truth
        s2 = SegmentFSEventStore({"PATH": path, "SEAL_INTERVAL_S": "3600"})
        evs = s2.find_since(APP, 0)
        assert [e.revision for e in evs] == [1, 2, 3, 4, 5]
        assert {e.event_id for e in evs} == set(ids)
        assert s2.latest_revision(APP) == 5
        s1._stop.set()  # reap the crashed store's sealer thread only
        s2.close()

    def test_torn_wal_tail_skipped(self, tmp_path):
        path = str(tmp_path / "seg")
        s1 = SegmentFSEventStore({"PATH": path, "SEAL_INTERVAL_S": "3600"})
        s1.init_app(APP)
        s1.insert_batch([rate("u1", "i1", 5), rate("u2", "i2", 4)], APP)
        # crash mid-append: a torn trailing record (never acked)
        (wal,) = (tmp_path / "seg" / "app_1").glob("wal-*.jsonl")
        with open(wal, "a") as f:
            f.write('[3, [["someid", "rate", "user", "u3"')
        s2 = SegmentFSEventStore({"PATH": path, "SEAL_INTERVAL_S": "3600"})
        assert [e.revision for e in s2.find_since(APP, 0)] == [1, 2]
        # the torn record was never acked, so its revision is safely
        # reassigned to the next insert
        new_id = s2.insert(rate("u3", "i3", 1), APP)
        assert s2.get(new_id, APP).revision == 3
        s1._stop.set()
        s2.close()

    def test_crash_between_seal_and_wal_truncate(self, tmp_path):
        """The seal-then-truncate window: segment published, WAL still
        holding the sealed records — reopen must dedupe by revision."""
        path = str(tmp_path / "seg")
        s1 = SegmentFSEventStore({"PATH": path, "SEAL_INTERVAL_S": "3600"})
        s1.init_app(APP)
        s1.insert_batch([rate(f"u{i}", "i1", i + 1) for i in range(4)], APP)
        (wal,) = (tmp_path / "seg" / "app_1").glob("wal-*.jsonl")
        saved = wal.read_bytes()
        s1.seal(APP)
        wal.write_bytes(saved)  # resurrect: as if the reclaim never ran
        s2 = SegmentFSEventStore({"PATH": path, "SEAL_INTERVAL_S": "3600"})
        evs = s2.find_since(APP, 0)
        assert [e.revision for e in evs] == [1, 2, 3, 4]  # no duplicates
        assert len({e.event_id for e in evs}) == 4
        s1._stop.set()
        s2.close()

    def test_revision_watermark_survives_deleted_tail(self, tmp_path):
        """Deleting the newest tail rows then sealing must not rewind
        the revision sequence across restart (the rev_floor file)."""
        path = str(tmp_path / "seg")
        s1 = SegmentFSEventStore({"PATH": path, "SEAL_INTERVAL_S": "3600"})
        s1.init_app(APP)
        ids = s1.insert_batch([rate(f"u{i}", "i1", 1) for i in range(3)], APP)
        s1.delete(ids[-1], APP)  # rev 3 now dead
        s1.seal(APP)  # sealed segment tops out at rev 2
        s2 = SegmentFSEventStore({"PATH": path, "SEAL_INTERVAL_S": "3600"})
        assert s2.latest_revision(APP) == 3
        nid = s2.insert(rate("u9", "i1", 1), APP)
        assert s2.get(nid, APP).revision == 4
        s1._stop.set()
        s2.close()


# ---------------------------------------------------------------------------
# Revision tail: exactly-once across seal + compaction
# ---------------------------------------------------------------------------


class TestRevisionTail:
    def test_exactly_once_across_seal(self, store):
        store.insert_batch([rate(f"u{i}", "i1", 1) for i in range(6)], APP)
        page = store.find_since(APP, 0, limit=3)
        cursor = page[-1].revision
        store.seal(APP)
        rest = store.find_since(APP, cursor)
        got = [e.revision for e in page + rest]
        assert got == [1, 2, 3, 4, 5, 6]

    def test_exactly_once_across_compaction(self, store):
        ids = []
        for k in range(4):  # four small segments
            ids += store.insert_batch(
                [rate(f"u{k}_{i}", "i1", 1) for i in range(3)], APP
            )
            store.seal(APP)
        page = store.find_since(APP, 0, limit=5)
        cursor = page[-1].revision
        assert store.compact(APP) == 3  # 4 → 1
        rest = store.find_since(APP, cursor)
        revs = [e.revision for e in page + rest]
        assert revs == list(range(1, 13))
        assert {e.event_id for e in page + rest} == set(ids)

    def test_compaction_drops_dead_rows(self, store):
        ids = store.insert_batch([rate(f"u{i}", "i1", 1) for i in range(4)], APP)
        store.seal(APP)
        store.insert_batch([rate(f"v{i}", "i1", 1) for i in range(4)], APP)
        store.seal(APP)
        store.delete(ids[0], APP)
        store.insert(rate("uX", "i2", 2, t=9).with_id(ids[1]), APP)  # overwrite
        store.seal(APP)
        st = store.segment_stats(APP)
        assert st["dead_rows"] == 2
        store.compact(APP)
        st = store.segment_stats(APP)
        assert st["segments"] == 1 and st["dead_rows"] == 0
        # 9 rows written; 1 deleted + 1 superseded by the overwrite
        assert st["sealed_rows"] == 7
        # content intact after the rewrite
        live = list(store.find(EventQuery(app_id=APP)))
        assert len(live) == 7
        assert store.get(ids[1], APP).entity_id == "uX"
        assert store.get(ids[0], APP) is None

    def test_revision_streams_shape(self, store):
        streams = store.revision_streams()
        assert len(streams) == 1
        key, s, shard = streams[0]
        assert s is store and shard is None

    def test_shard_filter_partitions(self, store):
        store.insert_batch([rate(f"u{i}", "i1", 1) for i in range(10)], APP)
        store.seal(APP)
        s0 = store.find_since(APP, 0, shard=(0, 2))
        s1 = store.find_since(APP, 0, shard=(1, 2))
        assert len(s0) + len(s1) == 10
        assert not ({e.event_id for e in s0} & {e.event_id for e in s1})


# ---------------------------------------------------------------------------
# find_frame: bit-identical parity with the row path
# ---------------------------------------------------------------------------


def _mixed_workload(rng, n=60):
    out = []
    for k in range(n):
        if k % 7 == 3:
            out.append(
                ev("$set", f"u{k % 5}", t=k,
                   properties=DataMap({"age": int(rng.randint(18, 60))}))
            )
        else:
            props = {"rating": float(rng.randint(1, 6))}
            if k % 5 == 0:
                props.pop("rating")  # absent → default applies
            out.append(
                ev("rate" if k % 3 else "buy", f"u{int(rng.randint(6))}",
                   t=int(rng.randint(48)), target_entity_type="item",
                   target_entity_id=f"i{int(rng.randint(9))}",
                   properties=DataMap(props))
            )
    return out


class TestFrameParity:
    @pytest.mark.parametrize("query_kw", [
        {},
        {"event_names": ["rate"]},
        {"event_names": ["rate", "buy"], "entity_type": "user"},
        {"start_time": T(10), "until_time": T(40)},
        {"target_entity_type": "item"},
        {"shard": (1, 3)},
        {"filter_target_absent": True},
    ])
    def test_bit_identical_vs_from_events(self, store, query_kw):
        rng = np.random.RandomState(7)
        events = _mixed_workload(rng)
        store.insert_batch(events[:25], APP)
        store.seal(APP)
        store.insert_batch(events[25:45], APP)
        store.seal(APP)
        store.insert_batch(events[45:], APP)  # unsealed tail
        q = EventQuery(app_id=APP, **query_kw)
        fast = store.find_frame(q, value_prop="rating", default_value=9.0)
        ref = EventFrame.from_events(
            store.find(q), value_prop="rating", default_value=9.0
        )
        frames_equal(fast, ref)
        if "shard" not in query_kw:
            assert len(fast) > 0
        else:
            # the three shard partitions cover the namespace exactly
            total = sum(
                len(
                    store.find_frame(
                        EventQuery(app_id=APP, shard=(i, 3)),
                        value_prop="rating", default_value=9.0,
                    )
                )
                for i in range(3)
            )
            assert total == len(
                store.find_frame(
                    EventQuery(app_id=APP), value_prop="rating",
                    default_value=9.0,
                )
            )

    def test_value_prop_overflow_fallback(self, store):
        """A numeric prop past the per-segment column cap still reads
        correctly through the sidecar fallback."""
        props = {f"p{k:02d}": float(k) for k in range(20)}
        store.insert_batch(
            [
                ev("rate", f"u{i}", t=i, target_entity_type="item",
                   target_entity_id="i0", properties=DataMap(dict(props)))
                for i in range(4)
            ],
            APP,
        )
        store.seal(APP)
        seg = store._ns[(APP, None)].segments[0]
        columnized = set(seg.footer["value_props"])
        overflow = sorted(set(props) - columnized)
        assert overflow, "cap did not bind — widen the workload"
        q = EventQuery(app_id=APP)
        fast = store.find_frame(q, value_prop=overflow[0], default_value=0.5)
        ref = EventFrame.from_events(
            store.find(q), value_prop=overflow[0], default_value=0.5
        )
        frames_equal(fast, ref)

    def test_sealed_cache_folds_only_tail(self, store):
        store.insert_batch([rate(f"u{i}", "i1", i + 1) for i in range(6)], APP)
        store.seal(APP)
        q = EventQuery(app_id=APP)
        store.find_frame(q, value_prop="rating")
        misses0 = store.frame_cache_stats["misses"]
        # tail-only growth: the sealed arrays are reused
        store.insert(rate("u9", "i2", 3, t=99), APP)
        frame = store.find_frame(q, value_prop="rating")
        assert store.frame_cache_stats["misses"] == misses0
        assert store.frame_cache_stats["hits"] >= 1
        assert "u9" in frame.entity_vocab
        # a seal changes the segment set: miss, then hit again
        store.seal(APP)
        store.find_frame(q, value_prop="rating")
        assert store.frame_cache_stats["misses"] == misses0 + 1

    def test_exotic_queries_fall_back(self, store):
        store.insert_batch([rate(f"u{i}", "i1", 1) for i in range(4)], APP)
        store.seal(APP)
        q = EventQuery(app_id=APP, entity_id="u1")
        frame = store.find_frame(q)
        assert len(frame) == 1
        with pytest.raises(StorageError):
            store.find_frame_parts(q)


# ---------------------------------------------------------------------------
# Target posting list (item fold-in index)
# ---------------------------------------------------------------------------


class TestTargetPosting:
    def test_target_read_prunes_segments(self, store):
        store.insert_batch([rate(f"u{i}", "iA", 1) for i in range(5)], APP)
        store.seal(APP)
        store.insert_batch([rate(f"u{i}", "iB", 1) for i in range(5)], APP)
        store.seal(APP)
        store.segments_scanned = 0
        got = list(
            store.find(
                EventQuery(
                    app_id=APP, target_entity_type="item",
                    target_entity_id="iB",
                )
            )
        )
        assert len(got) == 5
        assert all(e.target_entity_id == "iB" for e in got)
        # only the iB segment was touched (footer posting-set prune)
        assert store.segments_scanned == 1

    def test_memory_target_index(self):
        from predictionio_tpu.data.storage.memory import MemoryEventStore

        s = MemoryEventStore()
        ids = [s.insert(rate(f"u{i}", f"i{i % 2}", 1, t=i), APP) for i in range(6)]
        got = list(s.find(EventQuery(app_id=APP, target_entity_id="i1")))
        assert {e.entity_id for e in got} == {"u1", "u3", "u5"}
        # index follows deletes and overwrites
        s.delete(ids[1], APP)
        s.insert(rate("u3", "i0", 1, t=3).with_id(ids[3]), APP)
        got = list(s.find(EventQuery(app_id=APP, target_entity_id="i1")))
        assert {e.entity_id for e in got} == {"u5"}


# ---------------------------------------------------------------------------
# Compaction vs concurrent tail reads (the race the ISSUE names)
# ---------------------------------------------------------------------------


class TestConcurrency:
    @pytest.mark.parametrize("seed", [0])
    def test_compaction_vs_tail_read_race(self, tmp_path, seed):
        store = SegmentFSEventStore({
            "PATH": str(tmp_path / "race"),
            "SEAL_INTERVAL_S": "3600",
            "COMPACT_SEGMENTS": "2",
        })
        store.init_app(APP)
        n_total, batch = 400, 20
        errors: list[BaseException] = []
        seen: list[str] = []

        def writer():
            try:
                for b in range(n_total // batch):
                    store.insert_batch(
                        [
                            rate(f"u{b}_{i}", f"i{i % 3}", 1, t=b)
                            for i in range(batch)
                        ],
                        APP,
                    )
            except BaseException as e:  # noqa: BLE001 — fail the test
                errors.append(e)

        def maintainer():
            try:
                for _ in range(30):
                    store.seal(APP)
                    store.compact(APP)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        def reader():
            try:
                cursor = 0
                while len(seen) < n_total and not errors:
                    for e in store.find_since(APP, cursor, limit=64):
                        seen.append(e.event_id)
                        cursor = e.revision
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        threads = [
            threading.Thread(target=f, name=f"race-{f.__name__}")
            for f in (writer, maintainer, reader)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        assert len(seen) == n_total
        assert len(set(seen)) == n_total  # exactly once
        store.close()

    def test_background_sealer_thread_joins(self, tmp_path):
        store = SegmentFSEventStore({
            "PATH": str(tmp_path / "bg"),
            "SEAL_INTERVAL_S": "0.02",
            "SEAL_AGE_S": "0.01",
        })
        store.init_app(APP)
        store.insert_batch([rate(f"u{i}", "i1", 1) for i in range(8)], APP)
        deadline = dt.datetime.now() + dt.timedelta(seconds=10)
        while (
            store.segment_stats(APP)["tail_rows"]
            and dt.datetime.now() < deadline
        ):
            pass
        assert store.segment_stats(APP)["tail_rows"] == 0  # sealer ran
        sealer = store._sealer
        store.close()
        assert sealer is not None and not sealer.is_alive()


# ---------------------------------------------------------------------------
# Loader: SegmentStager device staging
# ---------------------------------------------------------------------------


class TestSegmentStager:
    def test_staged_parity_and_sealed_reuse(self, store):
        from predictionio_tpu.parallel.loader import SegmentStager

        store.insert_batch(
            [rate(f"u{i % 4}", f"i{i % 3}", i + 1, t=i) for i in range(12)],
            APP,
        )
        store.seal(APP)
        q = EventQuery(app_id=APP, event_names=["rate"])
        stager = SegmentStager()
        frame, staged = stager.stage(q_store := store, q, value_prop="rating")
        assert stager.stats["sealed_restage"] == 1
        np.testing.assert_array_equal(
            np.asarray(staged["entity_idx"]), frame.entity_idx
        )
        np.testing.assert_array_equal(
            np.asarray(staged["target_idx"]), frame.target_idx
        )
        np.testing.assert_array_equal(
            np.asarray(staged["value"]), frame.value
        )
        assert np.asarray(staged["valid"]).sum() == len(frame)
        bytes_full = stager.stats["bytes_staged"]
        # tail-only growth: only the tail rows cross to the device
        store.insert_batch([rate("u9", "i9", 2, t=99)], APP)
        frame2, staged2 = stager.stage(q_store, q, value_prop="rating")
        assert stager.stats["sealed_reuse"] == 1
        assert stager.stats["sealed_restage"] == 1
        tail_bytes = stager.stats["bytes_staged"] - bytes_full
        assert 0 < tail_bytes < bytes_full
        assert len(frame2) == len(frame) + 1
        np.testing.assert_array_equal(
            np.asarray(staged2["value"]), frame2.value
        )
        # the sealed prefix's codes were stable across the growth
        np.testing.assert_array_equal(
            np.asarray(staged2["entity_idx"])[: len(frame)],
            frame.entity_idx,
        )
        # a seal invalidates: full restage
        store.seal(APP)
        stager.stage(q_store, q, value_prop="rating")
        assert stager.stats["sealed_restage"] == 2

    def test_staged_training_matches_row_path(self, store, mesh8):
        from predictionio_tpu.models import als
        from predictionio_tpu.parallel.loader import SegmentStager

        rng = np.random.RandomState(3)
        store.insert_batch(
            [
                rate(f"u{int(rng.randint(12))}", f"i{int(rng.randint(8))}",
                     int(rng.randint(1, 6)), t=i)
                for i in range(120)
            ],
            APP,
        )
        store.seal(APP)
        q = EventQuery(app_id=APP, event_names=["rate"])
        stager = SegmentStager()
        frame, staged = stager.stage(store, q, value_prop="rating")
        rows, cols, vals = frame.interactions()
        params = als.ALSParams(rank=4, iterations=2)
        direct = als.train(
            rows, cols, vals, frame.n_entities, frame.n_targets, params
        )
        # staged arrays fetched back drive the same train
        r = np.asarray(staged["entity_idx"])
        c = np.asarray(staged["target_idx"])
        v = np.asarray(staged["value"])
        keep = c >= 0
        f2 = EventFrame(
            event_code=np.zeros(keep.sum(), np.int32),
            entity_idx=r[keep],
            target_idx=c[keep],
            time_ms=np.zeros(keep.sum(), np.int64),
            value=v[keep],
            event_vocab=frame.event_vocab,
            entity_vocab=frame.entity_vocab,
            target_vocab=frame.target_vocab,
        )
        rows2, cols2, vals2 = f2.interactions()
        via = als.train(
            rows2, cols2, vals2, frame.n_entities, frame.n_targets, params
        )
        np.testing.assert_allclose(
            direct.user_factors, via.user_factors, atol=1e-5
        )


# ---------------------------------------------------------------------------
# DataView delegation
# ---------------------------------------------------------------------------


class TestDataViewDelegation:
    def test_dataview_uses_segment_cache(self, tmp_path):
        from predictionio_tpu.data.storage.base import App
        from predictionio_tpu.data.storage.registry import (
            SourceConfig,
            Storage,
            StorageConfig,
        )
        from predictionio_tpu.data.view import DataView

        cfg = StorageConfig(
            sources={
                "M": SourceConfig("M", "memory", {}),
                "SEG": SourceConfig("SEG", "segmentfs", {
                    "PATH": str(tmp_path / "seg"),
                    "SEAL_INTERVAL_S": "3600",
                }),
            },
            repositories={
                "METADATA": "M", "EVENTDATA": "SEG", "MODELDATA": "M",
            },
        )
        storage = Storage(cfg)
        app_id = storage.get_meta_data_apps().insert(App(0, "segapp"))
        store = storage.get_events()
        store.init_app(app_id)
        store.insert_batch(
            [rate(f"u{i}", f"i{i % 2}", i + 1, t=i) for i in range(6)],
            app_id,
        )
        store.seal(app_id)
        view = DataView(view_dir=str(tmp_path / "view"))
        DataView.stats = {"hits": 0, "misses": 0}
        f1 = view.find_frame(storage, "segapp", value_prop="rating")
        assert DataView.stats == {"hits": 0, "misses": 1}
        view.find_frame(storage, "segapp", value_prop="rating")
        assert DataView.stats == {"hits": 1, "misses": 1}
        # tail growth is STILL a sealed-cache hit, with the tail folded
        store.insert(rate("u9", "i0", 2, t=50), app_id)
        f3 = view.find_frame(storage, "segapp", value_prop="rating")
        assert DataView.stats == {"hits": 2, "misses": 1}
        assert len(f3) == len(f1) + 1
        # no npz files were written (delegation skips the disk layer)
        assert not (tmp_path / "view").exists()
        store.close()


# ---------------------------------------------------------------------------
# Sharded batch replay routing (ISSUE 13 satellite)
# ---------------------------------------------------------------------------


class _RecordingStore:
    """Child-store stub recording batch req-ids and deduping on them —
    the remote daemon's req-id contract in miniature."""

    def __init__(self):
        from predictionio_tpu.data.storage.memory import MemoryEventStore

        self.inner = MemoryEventStore()
        self.req_ids: list[str] = []
        self._outcomes: dict[str, list[str]] = {}

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def insert_batch_with_req_id(self, events, app_id, channel_id, req_id):
        self.req_ids.append(req_id)
        if req_id in self._outcomes:  # replay the recorded outcome
            return self._outcomes[req_id]
        ids = self.inner.insert_batch(events, app_id, channel_id)
        self._outcomes[req_id] = ids
        return ids


class TestShardedBatchReqId:
    def test_routed_batches_under_stable_derived_ids(self):
        from predictionio_tpu.data.storage.sharded import ShardedEventStore

        children = [_RecordingStore(), _RecordingStore()]
        sharded = ShardedEventStore(stores=children)  # type: ignore[arg-type]
        events = [rate(f"u{i}", "i1", 1, t=i).with_id(f"e{i}") for i in range(10)]
        ids = sharded.insert_batch_with_req_id(events, APP, None, "walb-abc")
        assert ids == [f"e{i}" for i in range(10)]  # input order restored
        per_shard = [c.req_ids for c in children]
        assert per_shard[0] and per_shard[1]  # both shards got a group
        assert set(per_shard[0]) == {"walb-abc/s0"}
        assert set(per_shard[1]) == {"walb-abc/s1"}
        # a replay re-send forms the same groups under the same derived
        # ids, and each child's dedupe replays its recorded outcome
        ids2 = sharded.insert_batch_with_req_id(events, APP, None, "walb-abc")
        assert ids2 == ids
        total = sum(
            len(list(c.inner.find(EventQuery(app_id=APP))))
            for c in children
        )
        assert total == 10  # no duplicates from the re-send

    def test_children_without_capability_fall_back(self):
        from predictionio_tpu.data.storage.memory import MemoryEventStore
        from predictionio_tpu.data.storage.sharded import ShardedEventStore

        children = [MemoryEventStore(), MemoryEventStore()]
        sharded = ShardedEventStore(stores=children)
        events = [rate(f"u{i}", "i1", 1).with_id(f"e{i}") for i in range(6)]
        sharded.insert_batch_with_req_id(events, APP, None, "walb-x")
        # event-id stamping makes the replay an overwrite, not a dup
        sharded.insert_batch_with_req_id(events, APP, None, "walb-x")
        total = sum(
            len(list(c.find(EventQuery(app_id=APP)))) for c in children
        )
        assert total == 6


# ---------------------------------------------------------------------------
# Vectorized page materializer (ISSUE 14 satellite)
# ---------------------------------------------------------------------------


class TestEventsPage:
    def test_events_page_matches_per_row_materializer(self, store):
        """`_Segment.events_page` must produce Events identical to the
        per-row `seg.event(i)` path across every field, including None
        targets, tags, pr_id, and properties."""
        evs = [
            rate(f"u{i % 3}", f"i{i % 2}", i + 1, t=i) for i in range(6)
        ] + [
            ev("signup", f"u{i}", t=10 + i, properties=DataMap({"x": i}),
               tags=("a", "b"), pr_id=f"p{i}")
            for i in range(3)
        ]
        store.insert_batch(evs, APP)
        store.seal(APP)
        seg = store._namespace(APP, None).segments[0]
        rows = np.arange(seg.n_rows)
        page = seg.events_page(rows)
        for i in rows:
            a, b = page[i], seg.event(int(i))
            assert a.__dict__ == b.__dict__, i

    def test_generic_find_uses_pages_and_stays_exact(self, store):
        """The generic (no point filter) scan and the tail read return
        the same events before and after sealing — the paged decode is
        semantics-invisible (dead rows stay dead, order holds)."""
        store.insert_batch(
            [rate(f"u{i % 4}", f"i{i % 3}", i + 1, t=i) for i in range(20)],
            APP,
        )
        before = list(store.find(EventQuery(app_id=APP)))
        ids = [e.event_id for e in before]
        store.delete_batch(ids[3:5], APP)
        pre_seal = list(store.find(EventQuery(app_id=APP)))
        store.seal(APP)
        post_seal = list(store.find(EventQuery(app_id=APP)))
        assert [e.event_id for e in pre_seal] == [
            e.event_id for e in post_seal
        ]
        assert len(post_seal) == 18
        # find_since paging: exact tail with a small limit + shard
        tail = store.find_since(APP, 5, limit=4)
        assert [e.revision for e in tail] == [6, 7, 8, 9]
        sharded = store.find_since(APP, 0, limit=3, shard=(0, 2))
        from predictionio_tpu.data.storage import base as _b

        assert all(
            _b.shard_of(e.entity_id, 2) == 0 for e in sharded
        )
        assert len(sharded) == 3


# ---------------------------------------------------------------------------
# Cross-process writer guard (ISSUE 15 satellite, carried PR-13 item (c))
# ---------------------------------------------------------------------------


class TestWriterGuard:
    def test_second_writer_process_fails_fast(self, tmp_path):
        """A second PROCESS opening the same PATH must fail with a
        clear error instead of silently corrupting the WAL/segment
        sequence (fcntl.lockf is per-process, so the in-process
        crash-recovery tests above are unaffected)."""
        import subprocess
        import sys
        import textwrap

        path = str(tmp_path / "seg")
        store = SegmentFSEventStore(
            {"PATH": path, "SEAL_INTERVAL_S": "3600"}
        )
        store.init_app(APP)
        store.insert(rate("u1", "i1", 5), APP)
        child = subprocess.run(
            [sys.executable, "-c", textwrap.dedent(f"""
                from predictionio_tpu.data.storage.base import StorageError
                from predictionio_tpu.data.storage.segmentfs import (
                    SegmentFSEventStore,
                )
                try:
                    SegmentFSEventStore({{"PATH": {path!r}}})
                except StorageError as e:
                    assert "another process" in str(e), str(e)
                    print("REFUSED")
                else:
                    print("ACQUIRED")
            """)],
            capture_output=True, text=True, timeout=60,
        )
        assert child.returncode == 0, child.stderr
        assert "REFUSED" in child.stdout, (
            f"second writer process was not refused: {child.stdout!r} "
            f"{child.stderr!r}"
        )
        store.close()
        # after close the lock is released: a new process may open it
        child2 = subprocess.run(
            [sys.executable, "-c", textwrap.dedent(f"""
                from predictionio_tpu.data.storage.segmentfs import (
                    SegmentFSEventStore,
                )
                s = SegmentFSEventStore({{"PATH": {path!r}}})
                assert s.latest_revision({APP}) == 1
                s.close()
                print("ACQUIRED")
            """)],
            capture_output=True, text=True, timeout=60,
        )
        assert child2.returncode == 0, child2.stderr
        assert "ACQUIRED" in child2.stdout

    def test_same_process_crash_reopen_still_allowed(self, tmp_path):
        """The guard is cross-PROCESS only: an unclean in-process
        reopen (the crash-recovery pattern every TestCrashRecovery test
        uses) keeps working because POSIX record locks don't conflict
        within one process."""
        path = str(tmp_path / "seg")
        s1 = SegmentFSEventStore({"PATH": path, "SEAL_INTERVAL_S": "3600"})
        s1.init_app(APP)
        s1.insert(rate("u1", "i1", 5), APP)
        s2 = SegmentFSEventStore({"PATH": path, "SEAL_INTERVAL_S": "3600"})
        assert s2.latest_revision(APP) == 1
        s1._stop.set()
        s2.close()
