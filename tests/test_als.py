"""ALS kernel tests: solver correctness (vs direct normal-equation solves),
reconstruction quality, persistence, top-k serving, and sharded training on
the virtual 8-device mesh."""

import numpy as np
import pytest

from predictionio_tpu.data.store.bimap import BiMap
from predictionio_tpu.models import als


def make_synthetic(n_users=60, n_items=40, rank=5, density=0.3, seed=0, implicit=False):
    rng = np.random.default_rng(seed)
    true_u = rng.normal(size=(n_users, rank)).astype(np.float32)
    true_i = rng.normal(size=(n_items, rank)).astype(np.float32)
    mask = rng.random((n_users, n_items)) < density
    rows, cols = np.nonzero(mask)
    scores = np.sum(true_u[rows] * true_i[cols], axis=-1)
    if implicit:
        vals = (scores > 0).astype(np.float32) * 2.0
        keep = vals > 0
        rows, cols, vals = rows[keep], cols[keep], vals[keep]
    else:
        vals = scores + rng.normal(scale=0.01, size=scores.shape).astype(np.float32)
    return rows.astype(np.int32), cols.astype(np.int32), vals.astype(np.float32)


class TestExplicitALS:
    def test_reconstruction(self):
        rows, cols, vals = make_synthetic()
        params = als.ALSParams(
            rank=5, iterations=30, lambda_=0.01, implicit_prefs=False, cg_iterations=6
        )
        model = als.train(rows, cols, vals, 60, 40, params)
        pred = als.score_pairs(model, rows, cols)
        rmse = float(np.sqrt(np.mean((pred - vals) ** 2)))
        # data std is ~2.2; ALS convergence speed on this random-Gaussian
        # problem is init-dependent (exact-solve numpy ALS lands between
        # 0.1 and 0.4 after 30 sweeps depending on seed) — assert the fit
        # is far below the mean-predictor baseline
        baseline = float(np.std(vals))
        assert rmse < 0.25 * baseline, f"RMSE {rmse} vs baseline {baseline}"

    def test_reconstruction_easy(self):
        # low-rank, dense sampling: must fit to near the noise floor
        rows, cols, vals = make_synthetic(
            n_users=30, n_items=20, rank=2, density=0.7, seed=3
        )
        params = als.ALSParams(
            rank=2, iterations=20, lambda_=0.005, implicit_prefs=False, cg_iterations=4
        )
        model = als.train(rows, cols, vals, 30, 20, params)
        pred = als.score_pairs(model, rows, cols)
        rmse = float(np.sqrt(np.mean((pred - vals) ** 2)))
        assert rmse < 0.05, f"RMSE too high: {rmse}"

    def test_half_step_matches_direct_solve(self):
        """One explicit half-step must equal the closed-form per-user solve
        (Yᵀ_obs Y_obs + λ n_u I)⁻¹ Yᵀ_obs r."""
        rng = np.random.default_rng(1)
        n_users, n_items, k = 8, 12, 4
        rows = np.repeat(np.arange(n_users), 3).astype(np.int32)
        cols = rng.integers(0, n_items, len(rows)).astype(np.int32)
        vals = rng.normal(size=len(rows)).astype(np.float32)
        Y = rng.normal(size=(n_items, k)).astype(np.float32)
        lam = 0.1

        import jax.numpy as jnp
        from predictionio_tpu.models.als import _half_step_explicit

        order = np.argsort(rows, kind="stable")
        deg = np.bincount(rows, minlength=n_users).astype(np.float32)
        got = np.asarray(
            _half_step_explicit(
                jnp.asarray(Y),
                jnp.asarray(cols[order]),
                jnp.asarray(rows[order]),
                jnp.asarray(vals[order]),
                jnp.ones(len(rows), jnp.float32),
                jnp.asarray(deg),
                jnp.zeros((n_users, k), jnp.float32),
                lam,
                cg_iterations=30,
            )
        )
        for u in range(n_users):
            sel = rows == u
            Yu, ru = Y[cols[sel]], vals[sel]
            A = Yu.T @ Yu + lam * max(sel.sum(), 1) * np.eye(k, dtype=np.float32)
            expect = np.linalg.solve(A, Yu.T @ ru)
            np.testing.assert_allclose(got[u], expect, rtol=1e-3, atol=1e-4)


class TestImplicitALS:
    def test_half_step_matches_direct_solve(self):
        """One implicit half-step must equal (YᵀY + Yᵀ(Cu−I)Y + λI)⁻¹ YᵀCu·1."""
        rng = np.random.default_rng(2)
        n_users, n_items, k = 6, 10, 3
        rows = np.repeat(np.arange(n_users), 4).astype(np.int32)
        cols = rng.integers(0, n_items, len(rows)).astype(np.int32)
        # dedupe pairs to keep the direct solve simple
        keep = np.unique(rows.astype(np.int64) * n_items + cols, return_index=True)[1]
        rows, cols = rows[keep], cols[keep]
        conf = (1.0 + 2.0 * rng.random(len(rows))).astype(np.float32)
        Y = rng.normal(size=(n_items, k)).astype(np.float32)
        lam = 0.05

        import jax.numpy as jnp
        from predictionio_tpu.models.als import _half_step_implicit

        order = np.argsort(rows, kind="stable")
        got = np.asarray(
            _half_step_implicit(
                jnp.asarray(Y),
                jnp.asarray(cols[order]),
                jnp.asarray(rows[order]),
                jnp.asarray(conf[order]),
                jnp.ones(len(rows), jnp.float32),  # pref: all positive
                jnp.ones(len(rows), jnp.float32),
                jnp.zeros((n_users, k), jnp.float32),
                lam,
                cg_iterations=30,
            )
        )
        G = Y.T @ Y
        for u in range(n_users):
            sel = rows == u
            Yu, cu = Y[cols[sel]], conf[sel]
            A = G + Yu.T @ ((cu - 1.0)[:, None] * Yu) + lam * np.eye(k, dtype=np.float32)
            b = Yu.T @ cu
            expect = np.linalg.solve(A, b)
            np.testing.assert_allclose(got[u], expect, rtol=1e-3, atol=1e-4)

    def test_implicit_ranking_quality(self):
        rows, cols, vals = make_synthetic(implicit=True, density=0.4)
        params = als.ALSParams(rank=8, iterations=10, lambda_=0.01, alpha=2.0)
        model = als.train(rows, cols, vals, 60, 40, params)
        # observed items should outscore unobserved on average
        obs = als.score_pairs(model, rows, cols).mean()
        rng = np.random.default_rng(5)
        rnd_r = rng.integers(0, 60, 500)
        rnd_c = rng.integers(0, 40, 500)
        seen = set(zip(rows.tolist(), cols.tolist()))
        unseen = [(r, c) for r, c in zip(rnd_r, rnd_c) if (r, c) not in seen]
        un_r = np.array([r for r, _ in unseen])
        un_c = np.array([c for _, c in unseen])
        uns = als.score_pairs(model, un_r, un_c).mean()
        assert obs > uns + 0.2, f"observed {obs} vs unseen {uns}"

    def test_chunked_edges_match_single_shot(self):
        """Tiny edge_chunk_size forces the scan-accumulated path; factors
        must match the single-shot program (the chunked path is what runs
        at ML-20M scale to bound lane-padded gather intermediates)."""
        rows, cols, vals = make_synthetic(implicit=True, density=0.4)
        for implicit in (True, False):
            p1 = als.ALSParams(rank=6, iterations=4, implicit_prefs=implicit)
            p2 = als.ALSParams(
                rank=6, iterations=4, implicit_prefs=implicit,
                edge_chunk_size=97,  # ~8 chunks over ~720 edges
            )
            m1 = als.train(rows, cols, vals, 60, 40, p1)
            m2 = als.train(rows, cols, vals, 60, 40, p2)
            np.testing.assert_allclose(
                m1.user_factors, m2.user_factors, rtol=1e-4, atol=1e-5
            )
            np.testing.assert_allclose(
                m1.item_factors, m2.item_factors, rtol=1e-4, atol=1e-5
            )

    def test_implicit_dislike_scores_below_unseen(self):
        """MLlib trainImplicit semantics (ADVICE r1): a dislike (r=-1) is
        high-confidence zero-preference, so a disliked item must score
        BELOW a never-seen item, and training must stay stable for
        alpha > 1 (c = 1 + alpha*|r| keeps the operator SPD)."""
        rng = np.random.default_rng(9)
        n_users, n_items = 40, 30
        rows, cols, vals = [], [], []
        for u in range(n_users):
            liked = rng.choice(n_items // 2, 6, replace=False)
            disliked = n_items // 2 + rng.choice(n_items // 2, 3, replace=False)
            for i in liked:
                rows.append(u); cols.append(i); vals.append(1.0)
            for i in disliked:
                rows.append(u); cols.append(i); vals.append(-1.0)
        rows = np.array(rows, np.int32)
        cols = np.array(cols, np.int32)
        vals = np.array(vals, np.float32)
        params = als.ALSParams(rank=6, iterations=10, lambda_=0.01, alpha=4.0)
        model = als.train(rows, cols, vals, n_users, n_items, params)
        assert np.all(np.isfinite(model.user_factors))
        pos = als.score_pairs(
            model, rows[vals > 0], cols[vals > 0]
        ).mean()
        neg = als.score_pairs(
            model, rows[vals < 0], cols[vals < 0]
        ).mean()
        assert pos > 0.5, f"liked items should score high, got {pos}"
        assert neg < pos - 0.3, f"disliked {neg} not below liked {pos}"
        assert neg < 0.25, f"dislikes should be pulled toward 0, got {neg}"


class TestServing:
    def _model(self):
        rows, cols, vals = make_synthetic(implicit=True, density=0.4)
        model = als.train(
            rows, cols, vals, 60, 40,
            als.ALSParams(rank=8, iterations=8),
            user_vocab=BiMap.string_int([f"u{i}" for i in range(60)]),
            item_vocab=BiMap.string_int([f"i{i}" for i in range(40)]),
        )
        return model, rows, cols

    def test_recommend_shapes_and_exclusion(self):
        model, rows, cols = self._model()
        vals_, idx = als.recommend(model, np.array([0, 1, 2]), 5)
        assert vals_.shape == (3, 5) and idx.shape == (3, 5)
        # exclusion: ban user 0's top item and verify it no longer appears
        banned = int(idx[0, 0])
        mask = np.zeros((3, 40), dtype=bool)
        mask[0, banned] = True
        _, idx2 = als.recommend(model, np.array([0, 1, 2]), 5, exclude_mask=mask)
        assert banned not in idx2[0]

    def test_similar_items_excludes_self(self):
        model, *_ = self._model()
        vals_, idx = als.similar_items(model, np.array([3, 4]), 5)
        assert 3 not in idx[0] and 4 not in idx[1]
        assert np.all(vals_ <= 1.0 + 1e-5)

    def test_persistence_roundtrip(self):
        model, *_ = self._model()
        blob = model.to_bytes()
        loaded = als.ALSFactors.from_bytes(blob)
        np.testing.assert_array_equal(loaded.user_factors, model.user_factors)
        np.testing.assert_array_equal(loaded.item_factors, model.item_factors)
        assert loaded.user_vocab("u7") == model.user_vocab("u7")
        assert loaded.params == model.params


class TestShardedALS:
    def test_mesh_train_matches_single_device(self, mesh8):
        rows, cols, vals = make_synthetic(density=0.35)
        params = als.ALSParams(
            rank=4, iterations=5, implicit_prefs=False, cg_iterations=5
        )
        single = als.train(rows, cols, vals, 60, 40, params)
        sharded = als.train(rows, cols, vals, 60, 40, params, mesh=mesh8)
        # reduction order differs across shards, so factors drift over
        # sweeps — assert both runs fit the data equally well
        rmse_single = np.sqrt(
            np.mean((als.score_pairs(single, rows, cols) - vals) ** 2)
        )
        rmse_sharded = np.sqrt(
            np.mean((als.score_pairs(sharded, rows, cols) - vals) ** 2)
        )
        assert abs(rmse_single - rmse_sharded) < 0.05 * max(rmse_single, 1e-3)

    def test_mesh_single_sweep_exact(self, mesh8):
        # one sweep: sharded result differs only by reduction order
        rows, cols, vals = make_synthetic(density=0.35)
        params = als.ALSParams(
            rank=4, iterations=1, implicit_prefs=False, cg_iterations=5
        )
        single = als.train(rows, cols, vals, 60, 40, params)
        sharded = als.train(rows, cols, vals, 60, 40, params, mesh=mesh8)
        np.testing.assert_allclose(
            single.user_factors, sharded.user_factors, rtol=1e-3, atol=1e-4
        )

    def test_mesh_train_with_padding(self, mesh8):
        # edge count not divisible by device count exercises the pad path
        rows, cols, vals = make_synthetic(density=0.3, seed=7)
        n = (len(rows) // 8) * 8 + 3
        rows, cols, vals = rows[:n], cols[:n], vals[:n]
        params = als.ALSParams(rank=4, iterations=3, implicit_prefs=False)
        model = als.train(rows, cols, vals, 60, 40, params, mesh=mesh8)
        assert np.all(np.isfinite(model.user_factors))


def test_train_empty_interactions():
    """Zero events must yield a well-formed (regularized-init) model, not a
    deep IndexError from the windowed planner (code-review r3)."""
    from predictionio_tpu.models import als as _als

    m = _als.train(
        np.array([], np.int32), np.array([], np.int32),
        np.array([], np.float32), 5, 4,
        _als.ALSParams(rank=10, iterations=2),
    )
    assert m.user_factors.shape == (5, 10)
    assert m.item_factors.shape == (4, 10)
    assert np.all(np.isfinite(m.user_factors))
