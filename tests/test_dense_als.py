"""Dense-W ALS fast path (ops/dense.py + models/als.py dense solvers).

The dense path replaces the windowed edge pass with plain dense matmuls
over a device-resident rating matrix (the below-1%-density TPU move —
see ops/dense.py). These tests pin: pass-level exactness against numpy,
end-to-end agreement with the windowed path, the grid variant, resume,
and the auto-dispatch gate.
"""

import os

import numpy as np
import pytest

from predictionio_tpu.models import als
from predictionio_tpu.ops import dense as dense_ops


def _coo(n_users=300, n_items=180, n_edges=6000, seed=0, signed=False):
    rng = np.random.RandomState(seed)
    rows = rng.randint(0, n_users, n_edges).astype(np.int32)
    cols = rng.randint(0, n_items, n_edges).astype(np.int32)
    key = rows.astype(np.int64) * n_items + cols
    _, idx = np.unique(key, return_index=True)
    rows, cols = rows[idx], cols[idx]
    vals = (rng.randint(1, 11, len(rows)) / 2.0).astype(np.float32)
    if signed:
        vals *= rng.choice([-1.0, 1.0], len(rows)).astype(np.float32)
    return rows, cols, vals


def _pad_dims(n_users, n_items):
    nup = -(-n_users // dense_ops.ROW_BLOCK) * dense_ops.ROW_BLOCK
    nip = -(-n_items // dense_ops.COL_PAD) * dense_ops.COL_PAD
    return nup, nip


class TestDensePasses:
    """Pass-level exactness (f32 mode) against a per-edge numpy fold."""

    @pytest.mark.parametrize("implicit", [True, False])
    @pytest.mark.parametrize("signed", [False, True])
    def test_row_and_col_pass_match_numpy(self, implicit, signed):
        import jax.numpy as jnp

        if not implicit and signed:
            pytest.skip("explicit mode: sign carries through r itself")
        nu, ni, k, alpha = 100, 70, 8, 2.0
        rows, cols, vals = _coo(nu, ni, 900, seed=1, signed=signed)
        rng = np.random.RandomState(2)
        y = rng.randn(ni, k).astype(np.float32)
        x = rng.randn(nu, k).astype(np.float32)
        nup, nip = _pad_dims(nu, ni)
        yp = np.zeros((nip, k), np.float32)
        yp[:ni] = y
        xp = np.zeros((nup, k), np.float32)
        xp[:nu] = x
        r = dense_ops.densify(
            jnp.asarray(rows), jnp.asarray(cols), jnp.asarray(vals),
            n_rows_p=nup, n_cols_p=nip, dense_dtype="f32",
        )

        def w(v):
            if implicit:
                return (1.0 + alpha * abs(v)) * (v > 0), alpha * abs(v)
            return v, 1.0

        b_ref = np.zeros((nu, k))
        g_ref = np.zeros((nu, k, k))
        bc_ref = np.zeros((ni, k))
        gc_ref = np.zeros((ni, k, k))
        for r_, c_, v_ in zip(rows, cols, vals):
            w1, wg = w(v_)
            b_ref[r_] += w1 * y[c_]
            g_ref[r_] += wg * np.outer(y[c_], y[c_])
            bc_ref[c_] += w1 * x[r_]
            gc_ref[c_] += wg * np.outer(x[r_], x[r_])

        b, corr = dense_ops.dense_row_pass(
            r, jnp.asarray(yp), implicit=implicit, alpha=alpha,
            dense_dtype="f32",
        )
        np.testing.assert_allclose(
            np.asarray(b)[:nu], b_ref, rtol=1e-4, atol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(corr)[:nu].reshape(nu, k, k), g_ref,
            rtol=1e-4, atol=1e-4,
        )
        bc, gc = dense_ops.dense_col_pass(
            r, jnp.asarray(xp), implicit=implicit, alpha=alpha,
            dense_dtype="f32",
        )
        np.testing.assert_allclose(
            np.asarray(bc)[:ni], bc_ref, rtol=1e-4, atol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(gc)[:ni].reshape(ni, k, k), gc_ref,
            rtol=1e-4, atol=1e-4,
        )


class TestDenseTrain:
    @pytest.mark.parametrize("implicit", [True, False])
    def test_f32_dense_matches_windowed(self, implicit):
        rows, cols, vals = _coo()
        p = als.ALSParams(
            rank=8, iterations=6, implicit_prefs=implicit,
            alpha=2.0, lambda_=0.05,
        )
        ref = als.train(rows, cols, vals, 300, 180, p)  # windowed
        staged = als.stage_dense(
            rows, cols, vals, 300, 180, p, dense_dtype="f32"
        )
        uf, itf = staged.factors(*staged.run())
        # same math, different summation order + truncated CG → small
        # per-element drift compounds over alternating iterations; the
        # implicit operator is well-conditioned (tight), ALS-WR less so
        tol = 2e-3 if implicit else 5e-2
        np.testing.assert_allclose(
            uf, ref.user_factors, rtol=tol, atol=tol
        )
        np.testing.assert_allclose(
            itf, ref.item_factors, rtol=tol, atol=tol
        )

    def test_bf16_dense_is_finite_and_close(self):
        rows, cols, vals = _coo()
        p = als.ALSParams(rank=8, iterations=6, alpha=2.0, lambda_=0.05)
        ref = als.train(rows, cols, vals, 300, 180, p)
        staged = als.stage_dense(
            rows, cols, vals, 300, 180, p, dense_dtype="bf16"
        )
        uf, itf = staged.factors(*staged.run())
        assert np.all(np.isfinite(uf)) and np.all(np.isfinite(itf))
        c = np.corrcoef(uf.ravel(), ref.user_factors.ravel())[0, 1]
        assert c > 0.999

    def test_signed_feedback(self):
        """Dislikes (r<0): conf uses |r|, pref is 0 — dense weights must
        reproduce the windowed path's signed-implicit semantics."""
        rows, cols, vals = _coo(signed=True, seed=5)
        p = als.ALSParams(rank=6, iterations=5, alpha=1.5, lambda_=0.05)
        ref = als.train(rows, cols, vals, 300, 180, p)
        staged = als.stage_dense(
            rows, cols, vals, 300, 180, p, dense_dtype="f32"
        )
        uf, itf = staged.factors(*staged.run())
        np.testing.assert_allclose(
            uf, ref.user_factors, rtol=2e-3, atol=2e-3
        )

    def test_resume_matches_straight_run(self):
        rows, cols, vals = _coo(seed=7)
        p_full = als.ALSParams(rank=6, iterations=8)
        p_half = als.ALSParams(rank=6, iterations=4)
        full = als.stage_dense(
            rows, cols, vals, 300, 180, p_full, dense_dtype="f32"
        )
        uf_full, itf_full = full.factors(*full.run())
        first = als.stage_dense(
            rows, cols, vals, 300, 180, p_half, dense_dtype="f32"
        )
        uf1, itf1 = first.factors(*first.run())
        second = als.stage_dense(
            rows, cols, vals, 300, 180, p_half,
            init_factors=(uf1, itf1), dense_dtype="f32",
        )
        uf2, itf2 = second.factors(*second.run())
        np.testing.assert_allclose(uf2, uf_full, rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(itf2, itf_full, rtol=1e-3, atol=1e-4)


class TestDenseGrid:
    def test_grid_matches_per_point_runs(self):
        import jax.numpy as jnp

        rows, cols, vals = _coo(seed=9)
        lams = [0.01, 0.1, 1.0]
        base = als.ALSParams(rank=6, iterations=4)
        staged = als.stage_dense(
            rows, cols, vals, 300, 180, base, dense_dtype="f32"
        )
        kwargs = dict(staged.static_kwargs)
        kwargs.pop("lam"), kwargs.pop("alpha")
        kwargs.pop("mesh", None)
        kwargs.pop("pallas_mode", None)
        ufs, itfs = als._train_jit_dense_grid(
            *staged.device_args[:3],
            jnp.asarray(lams, jnp.float32),
            jnp.asarray([1.0] * len(lams), jnp.float32),
            **kwargs,
        )
        for g, lam in enumerate(lams):
            p = als.ALSParams(rank=6, iterations=4, lambda_=lam)
            one = als.stage_dense(
                rows, cols, vals, 300, 180, p, dense_dtype="f32"
            )
            uf, itf = one.factors(*one.run())
            np.testing.assert_allclose(
                np.asarray(ufs[g])[:300], uf, rtol=1e-4, atol=1e-5
            )
            np.testing.assert_allclose(
                np.asarray(itfs[g])[:180], itf, rtol=1e-4, atol=1e-5
            )


class TestDenseGate:
    def test_gate_conditions(self, monkeypatch):
        rows, cols, vals = _coo(n_edges=500, seed=3)
        p = als.ALSParams(rank=8)
        ok = lambda **kw: als.dense_eligible(
            rows, cols, vals, 300, 180, p, **kw
        )
        # auto mode: below the min-edge bar → windowed keeps the wheel
        monkeypatch.delenv("PIO_DENSE_ALS", raising=False)
        assert not ok()
        # forced on: eligible at any size
        monkeypatch.setenv("PIO_DENSE_ALS", "1")
        assert ok()
        # forced off wins
        monkeypatch.setenv("PIO_DENSE_ALS", "0")
        assert not ok()
        monkeypatch.setenv("PIO_DENSE_ALS", "1")
        # single-process meshes are allowed (shard_map'd dense train);
        # multi-host is not wired for dense R staging → fall back
        import jax as _jax

        class FakeMesh:
            pass

        monkeypatch.setattr(_jax, "process_count", lambda: 2)
        assert not ok(mesh=FakeMesh())
        monkeypatch.setattr(_jax, "process_count", lambda: 1)
        # memory budget
        monkeypatch.setenv("PIO_DENSE_ALS_BYTES", "1000")
        assert not ok()
        monkeypatch.delenv("PIO_DENSE_ALS_BYTES")
        # duplicate pairs fall back (dense would merge them)
        dup_rows = np.concatenate([rows, rows[:1]])
        dup_cols = np.concatenate([cols, cols[:1]])
        dup_vals = np.concatenate([vals, vals[:1]])
        assert not als.dense_eligible(
            dup_rows, dup_cols, dup_vals, 300, 180, p
        )
        # explicit with zero-valued ratings falls back
        z_vals = vals.copy()
        z_vals[0] = 0.0
        pe = als.ALSParams(rank=8, implicit_prefs=False)
        assert not als.dense_eligible(
            rows, cols, z_vals, 300, 180, pe
        )

    def test_train_dispatches_dense_when_forced(self, monkeypatch):
        rows, cols, vals = _coo(n_edges=800, seed=4)
        called = {}
        real = als._train_dense

        def spy(*a, **kw):
            called["yes"] = True
            return real(*a, **kw)

        monkeypatch.setattr(als, "_train_dense", spy)
        monkeypatch.setenv("PIO_DENSE_ALS", "1")
        m = als.train(rows, cols, vals, 300, 180, als.ALSParams(rank=6, iterations=2))
        assert called.get("yes")
        assert m.user_factors.shape == (300, 6)
        assert np.all(np.isfinite(m.user_factors))


class TestDenseSharded:
    def test_sharded_dense_matches_single_device(self, monkeypatch):
        """The shard_map'd dense train (R row-sharded over dp, item-side
        psum combine) must train the same factors as the single-device
        dense program — the init is generated replicated and sliced, so
        agreement is near-exact in f32."""
        from predictionio_tpu.parallel.mesh import make_mesh

        monkeypatch.setenv("PIO_DENSE_ALS", "1")
        rows, cols, vals = _coo(seed=11)
        p = als.ALSParams(rank=8, iterations=5, alpha=2.0, lambda_=0.05)
        single = als.stage_dense(
            rows, cols, vals, 300, 180, p, dense_dtype="f32"
        )
        uf1, itf1 = single.factors(*single.run())
        mesh = make_mesh()
        assert mesh.devices.size > 1
        sharded = als.stage_dense(
            rows, cols, vals, 300, 180, p, dense_dtype="f32", mesh=mesh
        )
        uf2, itf2 = sharded.factors(*sharded.run())
        np.testing.assert_allclose(uf2, uf1, rtol=5e-4, atol=5e-5)
        np.testing.assert_allclose(itf2, itf1, rtol=5e-4, atol=5e-5)

    def test_train_dispatches_sharded_dense_under_mesh(self, monkeypatch):
        from predictionio_tpu.parallel.mesh import make_mesh

        monkeypatch.setenv("PIO_DENSE_ALS", "1")
        rows, cols, vals = _coo(seed=12)
        m = als.train(
            rows, cols, vals, 300, 180,
            als.ALSParams(rank=6, iterations=3), mesh=make_mesh(),
        )
        assert m.user_factors.shape == (300, 6)
        assert np.all(np.isfinite(m.user_factors))
        # matches the meshless dense train
        m1 = als.train(
            rows, cols, vals, 300, 180, als.ALSParams(rank=6, iterations=3)
        )
        c = np.corrcoef(
            m.user_factors.ravel(), m1.user_factors.ravel()
        )[0, 1]
        assert c > 0.999


class TestFusedDenseKernel:
    """ops/dense_pallas.py — the fused one-R-read Pallas kernel.

    Default OFF by measurement (0.70 s vs 0.60 s per ML-20M train — its
    f32 weight-derivation VPU cost exceeds the saved int8 re-read; see
    resolve_mode). Kept correct and opt-in: these interpret-mode tests
    pin equivalence with the XLA dense passes, and the full-scale TPU
    numerics were validated at ML-20M (factor corr 0.99996 vs XLA)."""

    @pytest.mark.parametrize("implicit", [True, False])
    def test_interpret_matches_xla_passes(self, implicit):
        import jax.numpy as jnp

        from predictionio_tpu.ops import dense_pallas as dp

        rng = np.random.RandomState(3)
        nr, nc, k = 512, 512, 10
        q = rng.randint(-10, 11, (nr, nc)).astype(np.int8)
        q[rng.rand(nr, nc) > 0.05] = 0
        scale, alpha = 2.0, 1.7
        r_i8 = jnp.asarray(q)
        y = rng.randn(nc, k).astype(np.float32)
        z = (y[:, :, None] * y[:, None, :]).reshape(nc, k * k)
        x = rng.randn(nr, k).astype(np.float32)
        zx = (x[:, :, None] * x[:, None, :]).reshape(nr, k * k)
        asc = jnp.asarray(
            [alpha / scale if implicit else 1.0 / scale], jnp.float32
        )
        b_ref, c_ref = dense_ops.dense_row_pass(
            r_i8, jnp.asarray(y), implicit=implicit, alpha=alpha,
            dense_dtype="int8", row_block=256, scale=scale,
        )
        b_k, c_k = dp.fused_row_pass(
            r_i8, jnp.asarray(y), jnp.asarray(z.astype(np.float32)), asc,
            implicit=implicit, interpret=True, row_tile=256, col_tile=256,
        )
        # both are bf16-operand implementations of the same f32 math;
        # they differ only in rounding order
        np.testing.assert_allclose(
            np.asarray(b_k), np.asarray(b_ref), rtol=2e-2, atol=2.0
        )
        np.testing.assert_allclose(
            np.asarray(c_k), np.asarray(c_ref), rtol=2e-2, atol=4.0
        )
        b2_ref, c2_ref = dense_ops.dense_col_pass(
            r_i8, jnp.asarray(x), implicit=implicit, alpha=alpha,
            dense_dtype="int8", row_block=256, scale=scale,
        )
        b2_k, c2_k = dp.fused_col_pass(
            r_i8, jnp.asarray(x), jnp.asarray(zx.astype(np.float32)), asc,
            implicit=implicit, interpret=True, row_tile=256, col_tile=256,
        )
        np.testing.assert_allclose(
            np.asarray(b2_k), np.asarray(b2_ref), rtol=2e-2, atol=2.0
        )
        np.testing.assert_allclose(
            np.asarray(c2_k), np.asarray(c2_ref), rtol=2e-2, atol=4.0
        )

    def test_end_to_end_interpret_train(self, monkeypatch):
        monkeypatch.setenv("PIO_PALLAS_DENSE", "interpret")
        rows, cols, vals = _coo(seed=21)
        p = als.ALSParams(rank=8, iterations=4)
        staged = als.stage_dense(rows, cols, vals, 300, 180, p)
        assert staged.static_kwargs["pallas_mode"] == "interpret"
        uf, itf = staged.factors(*staged.run())
        assert np.all(np.isfinite(uf)) and np.all(np.isfinite(itf))
        monkeypatch.setenv("PIO_PALLAS_DENSE", "0")
        ref = als.stage_dense(rows, cols, vals, 300, 180, p)
        uf_r, itf_r = ref.factors(*ref.run())
        c = np.corrcoef(uf.ravel(), uf_r.ravel())[0, 1]
        assert c > 0.999
