"""Model lifecycle subsystem tests (ISSUE 5): registry CRUD/lineage/GC,
scheduler happy path + crash-resume + timeout + periodic retrain,
canary verdict math, the runtime-swap lock regression, variant-scoped
fault specs, and event-server ingest shedding."""

import datetime as _dt
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from predictionio_tpu.data.storage.base import AccessKey, App, EngineInstance
from predictionio_tpu.deploy.registry import ModelRegistry
from predictionio_tpu.deploy.rollout import (
    RolloutConfig,
    VariantWindow,
    sticky_candidate,
    verdict,
)
from predictionio_tpu.deploy.scheduler import (
    JobQueue,
    SchedulerConfig,
    TrainScheduler,
    storage_config_from_json,
    storage_config_to_json,
)
from predictionio_tpu.resilience import faults

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_DIR = os.path.dirname(TESTS_DIR)

VARIANT = {
    "id": "lc",
    "engineFactory": "sample_engine.Engine0Factory",
    "datasource": {"params": {"id": 1}},
    "preparator": {"params": {"id": 2}},
    "algorithms": [{"name": "algo0", "params": {"id": 3}}],
    "serving": {},
}

SLOW_VARIANT = {
    "id": "lcslow",
    "engineFactory": "sample_engine.SlowEngineFactory",
    "datasource": {"params": {"id": 1, "sleep_s": 20.0}},
    "preparator": {"params": {"id": 2}},
    "algorithms": [{"name": "", "params": {"id": 3}}],
}


def _instance(iid: str, variant: str = "lc", status: str = "COMPLETED"):
    now = _dt.datetime.now(_dt.timezone.utc)
    return EngineInstance(
        id=iid, status=status, start_time=now, end_time=now,
        engine_id=variant, engine_version="0", engine_variant=variant,
        engine_factory="sample_engine.Engine0Factory",
        algorithms_params=json.dumps([{"name": "algo0", "params": {"id": 3}}]),
    )


def _scheduler_config(tmp_path, **kw) -> SchedulerConfig:
    cfg = SchedulerConfig(
        poll_interval_s=0.1,
        heartbeat_interval_s=0.2,
        stale_after_s=1.0,
        log_dir=str(tmp_path / "job-logs"),
        child_env={
            "PYTHONPATH": os.pathsep.join([REPO_DIR, TESTS_DIR]),
            "JAX_PLATFORMS": "cpu",
        },
    )
    for k, v in kw.items():
        setattr(cfg, k, v)
    return cfg


def _wait_for(predicate, timeout=60.0, interval=0.1, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


# ---------------------------------------------------------------------------
# registry CRUD / lineage / GC
# ---------------------------------------------------------------------------


class TestModelRegistry:
    def test_register_requires_completed(self, fresh_storage):
        reg = ModelRegistry(fresh_storage)
        with pytest.raises(ValueError):
            reg.register(_instance("i0", status="ABORTED"))

    def test_crud_and_status_transitions(self, fresh_storage):
        reg = ModelRegistry(fresh_storage)
        v1 = reg.register(_instance("i1"))
        assert v1.status == "trained" and v1.parent_version is None
        assert reg.get(v1.id).to_dict() == v1.to_dict()
        assert reg.get("mv-nope") is None

        reg.promote(v1.id)
        assert reg.get(v1.id).status == "live"
        assert reg.live_version("lc", "lc").id == v1.id

        # lineage: versions registered while v1 is live point at it
        v2 = reg.register(_instance("i2"))
        assert v2.parent_version == v1.id
        assert [v.id for v in reg.lineage(v2.id)] == [v2.id, v1.id]

        # promote v2: v1 archived, not dropped
        reg.promote(v2.id)
        assert reg.get(v1.id).status == "archived"
        assert reg.live_version("lc", "lc").id == v2.id

        reg.rollback(v2.id, "bad p99")
        assert reg.get(v2.id).status == "rolled_back"
        assert reg.get(v2.id).reason == "bad p99"

        with pytest.raises(ValueError):
            reg.set_status(v1.id, "bogus")
        with pytest.raises(KeyError):
            reg.set_status("mv-nope", "live")

    def test_list_filters(self, fresh_storage):
        reg = ModelRegistry(fresh_storage)
        a = reg.register(_instance("ia", variant="va"))
        b = reg.register(_instance("ib", variant="vb"))
        reg.promote(b.id)
        assert {v.id for v in reg.list()} == {a.id, b.id}
        assert [v.id for v in reg.list(engine_id="va")] == [a.id]
        assert [v.id for v in reg.list(status="live")] == [b.id]

    def test_gc_retention(self, fresh_storage):
        from predictionio_tpu.data.storage.base import Model

        reg = ModelRegistry(fresh_storage)
        models = fresh_storage.get_model_data_models()
        versions = []
        for i in range(5):
            models.insert(Model(id=f"g{i}", models=b"blob"))
            versions.append(reg.register(_instance(f"g{i}")))
            time.sleep(0.002)  # distinct created_at ordering
        reg.promote(versions[0].id)  # oldest is live → GC-immune
        collected = reg.gc(keep=2, delete_blobs=True)
        # live v0 kept; newest 2 of the rest (v4, v3) kept; v1, v2 collected
        assert {v.id for v in collected} == {versions[1].id, versions[2].id}
        survivors = {v.id for v in reg.list()}
        assert survivors == {versions[0].id, versions[3].id, versions[4].id}
        assert models.get("g1") is None and models.get("g2") is None
        assert models.get("g0") is not None  # live blob survives


# ---------------------------------------------------------------------------
# scheduler: queue persistence, subprocess runs, crash-resume
# ---------------------------------------------------------------------------


class TestJobQueue:
    def test_storage_config_roundtrip(self, fresh_storage):
        restored = storage_config_from_json(
            storage_config_to_json(fresh_storage.config)
        )
        assert restored.repositories == fresh_storage.config.repositories
        assert set(restored.sources) == set(fresh_storage.config.sources)
        src = next(iter(restored.sources.values()))
        assert src.type == fresh_storage.config.sources[src.name].type

    def test_submit_and_backoff_gate(self, fresh_storage):
        q = JobQueue(fresh_storage)
        with pytest.raises(ValueError):
            q.submit({"id": "x"})  # engineFactory missing
        j = q.submit(VARIANT, timeout_s=9, period_s=60)
        got = q.get(j.id)
        assert got.status == "queued" and got.timeout_s == 9
        assert got.variant == VARIANT and got.period_s == 60
        assert [x.id for x in q.claimable()] == [j.id]
        q.update(j.id, not_before=time.time() + 3600)
        assert q.claimable() == []  # backoff gate holds it back

    def test_gc_keeps_active_and_newest_terminal(self, fresh_storage):
        q = JobQueue(fresh_storage)
        jobs = []
        for i in range(5):
            jobs.append(q.submit(VARIANT))
            time.sleep(0.002)  # distinct created_at ordering
        q.update(jobs[0].id, status="completed")
        q.update(jobs[1].id, status="failed")
        q.update(jobs[2].id, status="completed")
        q.update(jobs[3].id, status="running")
        purged = q.gc(keep=1)
        # running/queued immune; oldest terminal records beyond keep go
        assert purged == [jobs[0].id, jobs[1].id]
        assert {j.id for j in q.list()} == {
            jobs[2].id, jobs[3].id, jobs[4].id
        }

    def test_queue_survives_reopen(self, fresh_storage):
        """The queue is storage rows, not process state: a second
        JobQueue over the same stores sees the submitted job."""
        j = JobQueue(fresh_storage).submit(VARIANT)
        assert JobQueue(fresh_storage).get(j.id).variant == VARIANT


class TestSchedulerSubprocess:
    def test_job_trains_and_registers_version(self, fresh_storage, tmp_path):
        q = JobQueue(fresh_storage)
        job = q.submit(VARIANT)
        sched = TrainScheduler(fresh_storage, _scheduler_config(tmp_path))
        sched.start()
        try:
            _wait_for(
                lambda: q.get(job.id).status == "completed",
                timeout=90, what="job completion",
            )
        finally:
            sched.stop()
        done = q.get(job.id)
        assert done.instance_id and done.model_version
        assert done.log_path and os.path.exists(done.log_path)
        inst = fresh_storage.get_meta_data_engine_instances().get(
            done.instance_id
        )
        assert inst is not None and inst.status == "COMPLETED"
        version = ModelRegistry(fresh_storage).get(done.model_version)
        assert version is not None and version.status == "trained"
        assert version.instance_id == done.instance_id

        # ...and it shows up in `pio models list` (acceptance criterion)
        from predictionio_tpu.data.storage.registry import Storage
        from predictionio_tpu.tools import console

        Storage.set_instance(fresh_storage)
        try:
            assert console.main(["models", "list"]) == 0
        finally:
            Storage.set_instance(None)

    def test_worker_crash_requeues_and_completes(
        self, fresh_storage, tmp_path
    ):
        """Kill the worker mid-train: the job record stays `running`
        with a stale heartbeat; the next scheduler start re-queues it
        and it completes (the job itself is retried with a FAST variant
        by updating nothing — the slow sleep is in read_training, and
        the rerun simply runs it again, so keep the sleep short enough
        to finish)."""
        q = JobQueue(fresh_storage)
        slow = dict(SLOW_VARIANT)
        slow["datasource"] = {"params": {"id": 1, "sleep_s": 3.0}}
        job = q.submit(slow, max_attempts=3)
        cfg = _scheduler_config(tmp_path)
        sched1 = TrainScheduler(fresh_storage, cfg)
        sched1.start()
        try:
            _wait_for(
                lambda: q.get(job.id).status == "running",
                timeout=30, what="job to start",
            )
            # let the child get INTO the train (past interpreter boot)
            # then crash the worker: child killed, record untouched
            time.sleep(0.5)
        finally:
            sched1.stop(kill_child=True)
        stuck = q.get(job.id)
        assert stuck.status == "running"  # nobody cleaned up — a crash

        time.sleep(cfg.stale_after_s + 0.2)  # heartbeat goes stale
        sched2 = TrainScheduler(fresh_storage, cfg)
        assert sched2.resume_orphans() == [job.id]
        assert q.get(job.id).status == "queued"
        sched2.start()
        try:
            _wait_for(
                lambda: q.get(job.id).status == "completed",
                timeout=120, what="re-queued job completion",
            )
        finally:
            sched2.stop()
        done = q.get(job.id)
        assert done.model_version
        assert ModelRegistry(fresh_storage).get(done.model_version)

    def test_timeout_kills_and_fails_after_attempts(
        self, fresh_storage, tmp_path
    ):
        q = JobQueue(fresh_storage)
        job = q.submit(SLOW_VARIANT, timeout_s=6.0, max_attempts=1)
        sched = TrainScheduler(fresh_storage, _scheduler_config(tmp_path))
        ran = sched.run_pending_once()
        assert ran == 1
        done = q.get(job.id)
        assert done.status == "failed"
        assert "timeout" in (done.last_error or "")

    def test_train_failure_fails_fast_no_retry(self, fresh_storage, tmp_path):
        bad = dict(VARIANT, datasource={"params": {"id": 1, "error": True}})
        q = JobQueue(fresh_storage)
        job = q.submit(bad, max_attempts=3)
        sched = TrainScheduler(fresh_storage, _scheduler_config(tmp_path))
        sched.run_pending_once()
        done = q.get(job.id)
        # deterministic train failure: failed on attempt 1, not re-queued
        assert done.status == "failed" and done.attempt == 1
        with open(done.log_path, errors="replace") as f:
            assert "dirty" in f.read()  # sanity_check's message, per-job log

    def test_periodic_retrain_enqueues_next_run(
        self, fresh_storage, tmp_path
    ):
        q = JobQueue(fresh_storage)
        job = q.submit(VARIANT, period_s=3600.0)
        sched = TrainScheduler(fresh_storage, _scheduler_config(tmp_path))
        sched.run_pending_once()
        assert q.get(job.id).status == "completed"
        queued = q.list(status="queued")
        assert len(queued) == 1
        nxt = queued[0]
        assert nxt.variant == VARIANT and nxt.period_s == 3600.0
        assert nxt.not_before > time.time() + 3000  # gated a period out
        assert q.claimable() == []


# ---------------------------------------------------------------------------
# canary verdict math
# ---------------------------------------------------------------------------


def _stats(count=100, error_rate=0.0, p99_ms=10.0, **extra):
    return dict(
        count=count, errors=int(count * error_rate),
        error_rate=error_rate, p50_ms=p99_ms / 2, p99_ms=p99_ms, **extra
    )


class TestVerdictMath:
    CFG = RolloutConfig(
        fraction=0.1, min_requests=20, max_error_delta=0.05,
        max_p99_ratio=3.0, bake_s=60.0,
    )

    def test_waits_below_min_requests(self):
        action, _ = verdict(_stats(), _stats(count=19), self.CFG, 1e6)
        assert action == "wait"

    def test_error_delta_boundary(self):
        # delta exactly at the bound is allowed; above it rolls back
        ok, _ = verdict(
            _stats(error_rate=0.01), _stats(error_rate=0.06), self.CFG, 0
        )
        assert ok == "wait"
        bad, reason = verdict(
            _stats(error_rate=0.01), _stats(error_rate=0.07), self.CFG, 0
        )
        assert bad == "rollback" and "error-rate" in reason

    def test_p99_ratio_boundary(self):
        ok, _ = verdict(
            _stats(p99_ms=10.0), _stats(p99_ms=30.0), self.CFG, 0
        )
        assert ok == "wait"
        bad, reason = verdict(
            _stats(p99_ms=10.0), _stats(p99_ms=31.0), self.CFG, 0
        )
        assert bad == "rollback" and "p99" in reason

    def test_promote_after_bake(self):
        assert verdict(_stats(), _stats(), self.CFG, 59.9)[0] == "wait"
        assert verdict(_stats(), _stats(), self.CFG, 60.0)[0] == "promote"

    def test_shadow_agreement(self):
        cfg = RolloutConfig(
            min_requests=10, shadow=True, min_agreement=0.9, bake_s=60.0
        )
        live = _stats()
        ok, _ = verdict(
            live, _stats(agreement=0.95, shadow_count=50), cfg, 0
        )
        assert ok == "wait"
        bad, reason = verdict(
            live, _stats(agreement=0.5, shadow_count=50), cfg, 0
        )
        assert bad == "rollback" and "agreement" in reason
        # shadow judges on mirror volume, not on (zero) routed traffic
        wait, _ = verdict(
            live, _stats(count=0, shadow_count=5), cfg, 0
        )
        assert wait == "wait"

    def test_window_stats_and_stickiness(self):
        w = VariantWindow(window_s=30.0)
        for i in range(100):
            w.add(0.010 if i else 0.200, error=(i % 10 == 0))
        st = w.stats()
        assert st["count"] == 100 and st["errors"] == 10
        assert st["error_rate"] == pytest.approx(0.1)
        assert st["p99_ms"] >= st["p50_ms"] > 0
        # sticky routing: deterministic per body, splits the keyspace
        bodies = [f'{{"user":"u{i}"}}'.encode() for i in range(400)]
        picks = [sticky_candidate(b, 0.5) for b in bodies]
        assert picks == [sticky_candidate(b, 0.5) for b in bodies]
        assert 100 < sum(picks) < 300  # ~50% split


# ---------------------------------------------------------------------------
# variant-scoped fault specs (the e2e's instrument)
# ---------------------------------------------------------------------------


class TestScopedFaults:
    def teardown_method(self):
        faults.clear()

    def test_scoped_grammar_roundtrip(self):
        spec = faults.parse_spec("dispatch.device@candidate:error:1.0")
        assert spec.point == "dispatch.device"
        assert spec.scope == "candidate"
        assert spec.key() == "dispatch.device@candidate"
        # unscoped stays unscoped
        assert faults.parse_spec("model.load:error:0.5").scope is None

    def test_scoped_spec_fires_only_for_matching_scope(self):
        faults.install(
            faults.FaultSpec("dispatch.device", "error", 1.0,
                             scope="candidate")
        )
        assert faults.fire("dispatch.device") is None  # no scope given
        assert faults.fire("dispatch.device", scope="live") is None
        with pytest.raises(faults.FaultInjected):
            faults.fire("dispatch.device", scope="candidate")

    def test_unscoped_spec_matches_any_scope_unless_scoped_only(self):
        faults.install(faults.FaultSpec("dispatch.device", "error", 1.0))
        with pytest.raises(faults.FaultInjected):
            faults.fire("dispatch.device", scope="live")
        # scoped_only: the fallback path ignores scope-less specs
        assert faults.fire(
            "dispatch.device", scope="live", scoped_only=True
        ) is None


# ---------------------------------------------------------------------------
# runtime-swap lock: concurrent reloads must not interleave build_runtime
# ---------------------------------------------------------------------------


class TestReloadSwapLock:
    def test_concurrent_reloads_serialize(self, fresh_storage, monkeypatch):
        from predictionio_tpu.workflow import server as server_mod
        from predictionio_tpu.workflow.core import run_train
        from predictionio_tpu.workflow.server import (
            QueryServer,
            QueryServerConfig,
            latest_completed_runtime,
        )

        run_train(fresh_storage, VARIANT)
        runtime = latest_completed_runtime(fresh_storage, "lc", "0", "lc")
        srv = QueryServer(
            fresh_storage, runtime,
            QueryServerConfig(ip="127.0.0.1", port=0, micro_batch=False),
        )
        events: list[str] = []
        real = server_mod.latest_completed_runtime

        def slow_build(*a, **kw):
            events.append("enter")
            time.sleep(0.05)
            out = real(*a, **kw)
            events.append("exit")
            return out

        monkeypatch.setattr(
            server_mod, "latest_completed_runtime", slow_build
        )
        threads = [
            threading.Thread(target=srv.reload) for _ in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        # serialized: enter/exit strictly alternate — no interleaving
        assert events == ["enter", "exit", "enter", "exit"]


# ---------------------------------------------------------------------------
# admin-server control plane
# ---------------------------------------------------------------------------


class TestAdminControlPlane:
    @pytest.fixture()
    def admin(self, fresh_storage):
        from predictionio_tpu.tools.admin import AdminServer

        srv = AdminServer(fresh_storage, ip="127.0.0.1", port=0)
        port = srv.start()
        yield fresh_storage, port
        srv.stop()

    def _req(self, port, path, body=None, method=None):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}", data=data,
            headers={"Content-Type": "application/json"},
            method=method or ("POST" if data is not None else "GET"),
        )
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                raw = r.read().decode()
                try:
                    return r.status, json.loads(raw)
                except ValueError:
                    return r.status, raw
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read() or b"null")

    def test_jobs_endpoints(self, admin, tmp_path):
        storage, port = admin
        status, body = self._req(port, "/jobs", {"variant": VARIANT,
                                                 "period_s": 60})
        assert status == 201 and body["status"] == "queued"
        job_id = body["id"]
        status, listing = self._req(port, "/jobs")
        assert status == 200 and [j["id"] for j in listing] == [job_id]
        status, one = self._req(port, f"/jobs/{job_id}")
        assert status == 200 and one["period_s"] == 60
        assert self._req(port, "/jobs/job-nope")[0] == 404
        # no log yet → 404; after the record points at a real file → 200
        assert self._req(port, f"/jobs/{job_id}/logs")[0] == 404
        log_file = tmp_path / "j.log"
        log_file.write_text("train output here")
        JobQueue(storage).update(job_id, log_path=str(log_file))
        status, text = self._req(port, f"/jobs/{job_id}/logs")
        assert status == 200 and "train output" in text
        assert self._req(port, "/jobs", {"nope": 1})[0] == 400

    def test_models_and_rollout_endpoints(self, admin):
        storage, port = admin
        reg = ModelRegistry(storage)
        v1 = reg.register(_instance("a1"))
        v2 = reg.register(_instance("a2"))
        status, listing = self._req(port, "/models")
        assert status == 200 and {v["id"] for v in listing} == {v1.id, v2.id}
        status, one = self._req(port, f"/models/{v1.id}")
        assert status == 200 and one["lineage"] == [v1.id]
        status, body = self._req(port, f"/models/{v1.id}/promote", {})
        assert status == 200 and body["status"] == "live"
        status, body = self._req(
            port, f"/models/{v2.id}/rollback", {"reason": "nope"}
        )
        assert status == 200 and body["status"] == "rolled_back"
        assert self._req(port, "/models/mv-nope/promote", {})[0] == 404
        status, ro = self._req(port, "/rollout")
        assert status == 200
        assert [v["id"] for v in ro["live"]] == [v1.id]
        assert ro["canary"] == []
        # proxy: gated off by default (SSRF surface), validated when on
        assert self._req(
            port, "/rollout", {"url": "http://127.0.0.1:9"}
        )[0] == 403
        os.environ["PIO_ROLLOUT_PROXY"] = "1"
        try:
            assert self._req(port, "/rollout", {"action": "start"})[0] == 400
            assert self._req(
                port, "/rollout",
                {"url": "http://127.0.0.1:9/evil?x=", "action": "status"},
            )[0] == 400  # host-only urls; no path/query smuggling
            status, _ = self._req(
                port, "/rollout",
                {"url": "http://127.0.0.1:9", "action": "status"},
            )
            assert status == 502
        finally:
            del os.environ["PIO_ROLLOUT_PROXY"]


# ---------------------------------------------------------------------------
# event-server ingest shedding (ROADMAP PR-4 follow-up)
# ---------------------------------------------------------------------------


class TestIngestShedding:
    @pytest.fixture()
    def event_server(self, fresh_storage, tmp_path):
        from predictionio_tpu.data.api.server import (
            EventServer,
            EventServerConfig,
        )

        app_id = fresh_storage.get_meta_data_apps().insert(
            App(id=0, name="shedapp")
        )
        fresh_storage.get_events().init_app(app_id)
        fresh_storage.get_meta_data_access_keys().insert(
            AccessKey(key="SHEDKEY", app_id=app_id, events=())
        )
        srv = EventServer(fresh_storage, EventServerConfig(
            ip="127.0.0.1", port=0, wal_dir=str(tmp_path / "wal"),
            wal_replay_interval_s=30.0,  # replay stays out of the way
        ))
        port = srv.start()
        yield srv, port
        faults.clear()
        srv.stop()

    def _post(self, port, deadline_ms=None):
        headers = {"Content-Type": "application/json"}
        if deadline_ms is not None:
            headers["X-PIO-Deadline"] = str(deadline_ms)
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/events.json?accessKey=SHEDKEY",
            data=json.dumps({
                "event": "buy", "entityType": "user", "entityId": "u1",
            }).encode(),
            headers=headers, method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                return r.status, dict(r.headers), json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, dict(e.headers), json.loads(e.read() or b"null")

    def test_expired_deadline_is_shed_503(self, event_server):
        srv, port = event_server
        status, headers, body = self._post(port, deadline_ms=0)
        assert status == 503
        assert headers.get("Retry-After") == "1"
        assert "shed" in body["message"]
        # healthy requests still land
        status, _, body = self._post(port)
        assert status == 201 and "eventId" in body

    def test_spill_mode_never_sheds(self, event_server):
        """With storage down and the WAL absorbing events, an expired
        POST still gets the 202-into-WAL treatment — a fsync'd append
        beats a client retry loop against a degraded server."""
        srv, port = event_server
        faults.install(faults.FaultSpec("event.insert", "error", 1.0))
        status, _, body = self._post(port)  # first spill: WAL now pending
        assert status == 202 and "walId" in body
        status, _, body = self._post(port, deadline_ms=0)
        assert status == 202 and "walId" in body  # NOT shed
        faults.clear()


# ---------------------------------------------------------------------------
# ISSUE 6 satellites: registry-fold compaction + scheduler concurrency
# ---------------------------------------------------------------------------


class TestFoldCompaction:
    def _n_events(self, storage, entity_id):
        from predictionio_tpu.data.storage.base import EventQuery
        from predictionio_tpu.deploy.registry import (
            LIFECYCLE_APP_ID,
            VERSION_ENTITY,
        )

        return len(list(storage.get_events().find(EventQuery(
            app_id=LIFECYCLE_APP_ID, entity_type=VERSION_ENTITY,
            entity_id=entity_id,
        ))))

    def test_compact_preserves_fold_and_bounds_events(self, fresh_storage):
        from predictionio_tpu.deploy.registry import (
            LifecycleRecordStore,
            VERSION_ENTITY,
        )

        reg = ModelRegistry(fresh_storage)
        v = reg.register(_instance("ci1"))
        for i in range(6):
            reg.set_status(v.id, "archived" if i % 2 else "trained",
                           reason=f"r{i}")
        before = reg.get(v.id).to_dict()
        assert self._n_events(fresh_storage, v.id) >= 7
        store = LifecycleRecordStore(fresh_storage)
        # quiescence guard: a freshly-written record does NOT compact —
        # a concurrent writer's update landing mid-compaction would be
        # outranked by the snapshot and silently reverted
        assert store.compact(VERSION_ENTITY, v.id) == 0
        removed = store.compact(VERSION_ENTITY, v.id, min_age_s=0.0)
        assert removed >= 7
        # fold → ONE snapshot event, identical record
        assert self._n_events(fresh_storage, v.id) == 1
        assert reg.get(v.id).to_dict() == before
        # further updates still fold on top of the snapshot
        reg.set_status(v.id, "live")
        assert reg.get(v.id).status == "live"

    def test_gc_runs_compaction(self, fresh_storage):
        reg = ModelRegistry(fresh_storage)
        v = reg.register(_instance("ci2"))
        for i in range(10):
            reg.set_status(v.id, "trained", reason=f"r{i}")
        assert self._n_events(fresh_storage, v.id) >= 11
        # gc's sweep skips this still-hot record (quiescence guard)...
        reg.gc(keep=5)
        assert self._n_events(fresh_storage, v.id) >= 11
        # ...and compacts it once it has gone quiet
        reg.compact(min_age_s=0.0)
        assert self._n_events(fresh_storage, v.id) == 1
        assert reg.get(v.id).reason == "r9"

    def test_compact_below_threshold_is_noop(self, fresh_storage):
        from predictionio_tpu.deploy.registry import (
            LifecycleRecordStore,
            VERSION_ENTITY,
        )

        reg = ModelRegistry(fresh_storage)
        v = reg.register(_instance("ci3"))
        store = LifecycleRecordStore(fresh_storage)
        assert store.compact_all(VERSION_ENTITY, min_events=8) == 0
        assert self._n_events(fresh_storage, v.id) == 1


class TestSchedulerConcurrency:
    def test_two_engines_run_concurrently(self, fresh_storage, tmp_path):
        """max_concurrent=2: two different engines' slow trains are
        observed `running` at the same time (with one worker the second
        would queue behind the first's full train)."""
        q = JobQueue(fresh_storage)
        slow_a = dict(
            SLOW_VARIANT, id="lcslowa",
            datasource={"params": {"id": 1, "sleep_s": 6.0}},
        )
        slow_b = dict(slow_a, id="lcslowb")
        ja, jb = q.submit(slow_a), q.submit(slow_b)
        sched = TrainScheduler(
            fresh_storage, _scheduler_config(tmp_path, max_concurrent=2)
        )
        sched.start()
        try:
            _wait_for(
                lambda: len(q.list(status="running")) == 2,
                timeout=60, what="both engines training concurrently",
            )
            _wait_for(
                lambda: all(
                    q.get(j.id).status == "completed" for j in (ja, jb)
                ),
                timeout=120, what="both jobs completing",
            )
        finally:
            sched.stop()

    def test_same_engine_serializes(self, fresh_storage, tmp_path):
        """Two jobs for ONE engine never run concurrently even with
        max_concurrent=2 — concurrent trains of the same engine would
        race the latest-COMPLETED pointer deploys read."""
        q = JobQueue(fresh_storage)
        slow = dict(
            SLOW_VARIANT,
            datasource={"params": {"id": 1, "sleep_s": 3.0}},
        )
        j1, j2 = q.submit(slow), q.submit(slow)
        sched = TrainScheduler(
            fresh_storage, _scheduler_config(tmp_path, max_concurrent=2)
        )
        max_running = 0
        sched.start()
        try:
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                max_running = max(
                    max_running, len(q.list(status="running"))
                )
                if all(
                    q.get(j.id).status == "completed" for j in (j1, j2)
                ):
                    break
                time.sleep(0.05)
        finally:
            sched.stop()
        assert all(q.get(j.id).status == "completed" for j in (j1, j2))
        assert max_running <= 1, (
            f"same-engine jobs overlapped ({max_running} running at once)"
        )
