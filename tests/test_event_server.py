"""Black-box HTTP tests of the Event Server (ports of reference
data/src/test/.../api/EventServiceSpec.scala + shell tests data/test.sh)."""

import json
import urllib.error
import urllib.request

import pytest

from predictionio_tpu.data.api.plugins import INPUT_BLOCKER
from predictionio_tpu.data.api.server import EventServer, EventServerConfig
from predictionio_tpu.data.storage.base import AccessKey, App, Channel


def req(port, path, method="GET", body=None, form=False):
    url = f"http://127.0.0.1:{port}{path}"
    data = None
    headers = {}
    if body is not None:
        if form:
            from urllib.parse import urlencode

            data = urlencode(body).encode()
            headers["Content-Type"] = "application/x-www-form-urlencoded"
        else:
            data = json.dumps(body).encode()
            headers["Content-Type"] = "application/json"
    r = urllib.request.Request(url, data=data, headers=headers, method=method)
    try:
        with urllib.request.urlopen(r, timeout=10) as resp:
            return resp.status, json.loads(resp.read().decode() or "null")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode() or "null")


class RejectBlocker:
    plugin_name = "reject-spam"
    plugin_type = INPUT_BLOCKER

    def process(self, event_json, context):
        if event_json.get("event") == "spam":
            raise ValueError("spam is blocked")


@pytest.fixture()
def server(fresh_storage):
    apps = fresh_storage.get_meta_data_apps()
    app_id = apps.insert(App(id=0, name="srvapp"))
    fresh_storage.get_events().init_app(app_id)
    keys = fresh_storage.get_meta_data_access_keys()
    keys.insert(AccessKey(key="KEY", app_id=app_id, events=()))
    keys.insert(AccessKey(key="RATEONLY", app_id=app_id, events=("rate",)))
    ch_id = fresh_storage.get_meta_data_channels().insert(
        Channel(id=0, name="ch1", app_id=app_id)
    )
    fresh_storage.get_events().init_app(app_id, ch_id)
    srv = EventServer(
        fresh_storage,
        EventServerConfig(ip="127.0.0.1", port=0, stats=True, plugins=[RejectBlocker()]),
    )
    port = srv.start()
    yield port
    srv.stop()


EVENT = {
    "event": "rate",
    "entityType": "user",
    "entityId": "u1",
    "targetEntityType": "item",
    "targetEntityId": "i1",
    "properties": {"rating": 4.5},
}


def test_status_alive(server):
    status, body = req(server, "/")
    assert (status, body) == (200, {"status": "alive"})


def test_auth_required_and_invalid(server):
    status, body = req(server, "/events.json", "POST", EVENT)
    assert status == 401
    status, body = req(server, "/events.json?accessKey=WRONG", "POST", EVENT)
    assert status == 401
    assert "Invalid accessKey" in body["message"]


def test_insert_get_delete_roundtrip(server):
    status, body = req(server, "/events.json?accessKey=KEY", "POST", EVENT)
    assert status == 201
    eid = body["eventId"]

    status, body = req(server, f"/events/{eid}.json?accessKey=KEY")
    assert status == 200
    assert body["event"] == "rate" and body["entityId"] == "u1"
    assert body["properties"] == {"rating": 4.5}

    status, body = req(server, f"/events/{eid}.json?accessKey=KEY", "DELETE")
    assert (status, body) == (200, {"message": "Found"})
    status, _ = req(server, f"/events/{eid}.json?accessKey=KEY")
    assert status == 404


def test_reserved_event_name_rejected(server):
    bad = dict(EVENT, event="$asdf")
    status, body = req(server, "/events.json?accessKey=KEY", "POST", bad)
    assert status == 400
    assert "reserved" in body["message"]


def test_malformed_json_rejected(server):
    r = urllib.request.Request(
        f"http://127.0.0.1:{server}/events.json?accessKey=KEY",
        data=b"{not json",
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(r, timeout=10)
    assert ei.value.code == 400


def test_event_whitelist(server):
    status, _ = req(server, "/events.json?accessKey=RATEONLY", "POST", EVENT)
    assert status == 201
    buy = dict(EVENT, event="buy")
    status, body = req(server, "/events.json?accessKey=RATEONLY", "POST", buy)
    assert status == 403
    assert "not allowed" in body["message"]


def test_channel_routing(server):
    status, body = req(server, "/events.json?accessKey=KEY&channel=ch1", "POST", EVENT)
    assert status == 201
    # event lives in the channel namespace, not the default one
    status, body = req(server, "/events.json?accessKey=KEY&channel=ch1")
    assert status == 200 and len(body) == 1
    status, body = req(server, "/events.json?accessKey=KEY&channel=nope", "POST", EVENT)
    assert status == 401
    assert "Invalid channel" in body["message"]


def test_batch_mixed_and_limit(server):
    batch = [EVENT, dict(EVENT, event="$bad"), dict(EVENT, entityId="")]
    status, body = req(server, "/batch/events.json?accessKey=KEY", "POST", batch)
    assert status == 200
    assert [r["status"] for r in body] == [201, 400, 400]
    assert "eventId" in body[0]

    status, body = req(
        server, "/batch/events.json?accessKey=KEY", "POST", [EVENT] * 51
    )
    assert status == 400
    assert "less than or equal to 50" in body["message"]


def test_get_events_filters(server):
    for i in range(5):
        req(
            server,
            "/events.json?accessKey=KEY",
            "POST",
            dict(EVENT, entityId=f"u{i}", event="view" if i % 2 else "rate"),
        )
    status, body = req(server, "/events.json?accessKey=KEY&event=rate")
    assert status == 200
    assert all(e["event"] == "rate" for e in body)
    status, body = req(server, "/events.json?accessKey=KEY&limit=2")
    assert len(body) == 2
    status, body = req(server, "/events.json?accessKey=KEY&entityId=u3")
    assert len(body) == 1 and body[0]["entityId"] == "u3"
    status, _ = req(server, "/events.json?accessKey=KEY&entityId=ghost")
    assert status == 404


def test_stats(server):
    req(server, "/events.json?accessKey=KEY", "POST", EVENT)
    status, body = req(server, "/stats.json?accessKey=KEY")
    assert status == 200
    counts = body["hours"][0]["counts"]
    assert any(c["event"] == "rate" and c["count"] >= 1 for c in counts)


def test_input_blocker(server):
    spam = dict(EVENT, event="spam")
    status, body = req(server, "/events.json?accessKey=KEY", "POST", spam)
    assert status == 403
    assert "spam is blocked" in body["message"]
    # and the event is NOT stored
    status, _ = req(server, "/events.json?accessKey=KEY&event=spam")
    assert status == 404


def test_keepalive_error_then_success(server):
    """An error response must drain the request body — otherwise the next
    request on the same keep-alive connection desyncs."""
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", server, timeout=10)
    body = json.dumps(EVENT)
    conn.request(
        "POST", "/events.json?accessKey=WRONG", body=body,
        headers={"Content-Type": "application/json"},
    )
    r1 = conn.getresponse()
    r1.read()
    assert r1.status == 401
    conn.request(
        "POST", "/events.json?accessKey=KEY", body=body,
        headers={"Content-Type": "application/json"},
    )
    r2 = conn.getresponse()
    out = json.loads(r2.read().decode())
    assert r2.status == 201, out
    conn.close()


def test_webhooks_examplejson(server):
    payload = {
        "type": "userActionItem",
        "userId": "as34smg4",
        "itemId": "kfjd312bc",
        "timestamp": "2026-01-02T00:30:12.984Z",
        "properties": {"context": "mobile"},
    }
    status, body = req(
        server, "/webhooks/examplejson.json?accessKey=KEY", "POST", payload
    )
    assert status == 201
    eid = body["eventId"]
    status, body = req(server, f"/events/{eid}.json?accessKey=KEY")
    assert body["event"] == "userActionItem"
    assert body["targetEntityId"] == "kfjd312bc"

    # existence check + unknown connector
    status, body = req(server, "/webhooks/examplejson.json?accessKey=KEY")
    assert (status, body) == (200, {})
    status, _ = req(server, "/webhooks/nope.json?accessKey=KEY")
    assert status == 404


def test_webhooks_segmentio(server):
    payload = {
        "type": "track",
        "userId": "user123",
        "event": "Signed Up",
        "properties": {"plan": "Pro"},
        "timestamp": "2026-02-23T22:28:55.111Z",
    }
    status, body = req(
        server, "/webhooks/segmentio.json?accessKey=KEY", "POST", payload
    )
    assert status == 201
    status, body = req(server, f"/events/{body['eventId']}.json?accessKey=KEY")
    assert body["event"] == "track"
    assert body["properties"]["event"] == "Signed Up"


def test_webhooks_mailchimp_form(server):
    form = {
        "type": "subscribe",
        "fired_at": "2026-02-23 21:35:57",
        "data[id]": "8a25ff1d98",
        "data[list_id]": "a6b5da1054",
        "data[email]": "api@mailchimp.com",
    }
    status, body = req(
        server, "/webhooks/mailchimp.form?accessKey=KEY", "POST", form, form=True
    )
    assert status == 201
    status, body = req(server, f"/events/{body['eventId']}.json?accessKey=KEY")
    assert body["event"] == "subscribe"
    assert body["entityId"] == "8a25ff1d98"


# ---------------------------------------------------------------------------
# segmentfs admin endpoints (ISSUE 14 satellite)
# ---------------------------------------------------------------------------


@pytest.fixture()
def seg_server(tmp_path):
    from predictionio_tpu.data.storage.registry import (
        SourceConfig,
        Storage,
        StorageConfig,
    )

    cfg = StorageConfig(
        sources={
            "M": SourceConfig("M", "memory", {}),
            "SEG": SourceConfig("SEG", "segmentfs", {
                "PATH": str(tmp_path / "seg"),
                "SEAL_INTERVAL_S": "3600",
            }),
        },
        repositories={
            "METADATA": "M", "EVENTDATA": "SEG", "MODELDATA": "M",
        },
    )
    storage = Storage(cfg)
    app_id = storage.get_meta_data_apps().insert(App(id=0, name="segapp"))
    storage.get_events().init_app(app_id)
    storage.get_meta_data_access_keys().insert(
        AccessKey(key="SKEY", app_id=app_id, events=())
    )
    srv = EventServer(
        storage, EventServerConfig(ip="127.0.0.1", port=0)
    )
    port = srv.start()
    yield port, storage, app_id
    srv.stop()


def test_segments_admin_endpoints(seg_server):
    port, storage, app_id = seg_server
    # ingest some events, then inspect / seal / compact over HTTP
    for i in range(5):
        status, _ = req(
            port, "/events.json?accessKey=SKEY", "POST",
            dict(EVENT, entityId=f"u{i}"),
        )
        assert status == 201
    status, st = req(port, "/segments/stats?accessKey=SKEY")
    assert status == 200
    assert st["tail_rows"] == 5 and st["segments"] == 0
    status, body = req(port, "/segments/seal?accessKey=SKEY", "POST")
    assert status == 200 and body["sealedRows"] == 5
    status, st = req(port, "/segments/stats?accessKey=SKEY")
    assert st["tail_rows"] == 0 and st["segments"] == 1
    status, body = req(port, "/segments/compact?accessKey=SKEY", "POST")
    assert status == 200 and body["segmentsMerged"] == 0
    # auth still gates the admin surface
    status, _ = req(port, "/segments/stats?accessKey=WRONG")
    assert status == 401


def test_segments_endpoints_404_without_segmentfs(server):
    status, body = req(server, "/segments/stats?accessKey=KEY")
    assert status == 404
    assert "segmentfs" in body["message"]
    status, _ = req(server, "/segments/seal?accessKey=KEY", "POST")
    assert status == 404
