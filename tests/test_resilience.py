"""Resilience layer unit tests (ISSUE 4): breaker state machine, backoff
schedule under a deadline budget, WAL replay dedupe/resume, fault-point
determinism, deadline header plumbing, dispatcher timeout-leak fix."""

import threading
import time

import pytest

from predictionio_tpu.resilience import breaker as breaker_mod
from predictionio_tpu.resilience import deadline as deadline_mod
from predictionio_tpu.resilience import faults as faults_mod
from predictionio_tpu.resilience.breaker import CircuitBreaker
from predictionio_tpu.resilience.faults import (
    FaultInjected,
    FaultRegistry,
    FaultSpec,
    FaultSpecError,
    parse_specs,
)
from predictionio_tpu.resilience.retry import RetryPolicy
from predictionio_tpu.resilience.wal import EventWAL
from predictionio_tpu.obs.registry import MetricsRegistry


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _breaker(threshold=3, cooldown=10.0):
    clock = FakeClock()
    reg = MetricsRegistry()
    b = CircuitBreaker(
        "test-endpoint", failure_threshold=threshold, cooldown_s=cooldown,
        registry=reg, clock=clock,
    )
    return b, clock, reg


def test_breaker_opens_after_threshold_and_fails_fast():
    b, clock, reg = _breaker(threshold=3)
    assert b.state == "closed"
    for _ in range(2):
        assert b.allow()
        b.record_failure()
    assert b.state == "closed"  # under threshold
    assert b.allow()
    b.record_failure()  # third consecutive failure trips it
    assert b.state == "open"
    assert not b.allow()  # fail fast, no probe before cooldown
    assert reg.gauge(
        "resilience_breaker_state", "", ("endpoint", "dao")
    ).value(endpoint="test-endpoint", dao="") == 1.0


def test_breaker_half_open_probe_recovers():
    b, clock, reg = _breaker(threshold=1, cooldown=10.0)
    b.allow()
    b.record_failure()
    assert b.state == "open"
    clock.advance(10.1)
    assert b.state == "half_open"
    assert b.allow()  # the recovery probe
    assert not b.allow()  # only ONE probe in flight
    b.record_success()
    assert b.state == "closed"
    assert b.allow()
    # transition counter saw closed→open→half_open→closed
    ctr = reg.counter(
        "resilience_breaker_transitions_total", "",
        ("endpoint", "dao", "state"),
    )
    assert ctr.value(endpoint="test-endpoint", dao="", state="open") == 1
    assert ctr.value(
        endpoint="test-endpoint", dao="", state="half_open"
    ) == 1
    assert ctr.value(endpoint="test-endpoint", dao="", state="closed") == 1


def test_breaker_failed_probe_reopens():
    b, clock, _ = _breaker(threshold=1, cooldown=5.0)
    b.record_failure()
    clock.advance(5.1)
    assert b.allow()  # probe
    b.record_failure()  # probe failed
    assert b.state == "open"
    assert not b.allow()  # a fresh cooldown started
    clock.advance(5.1)
    assert b.allow()
    b.record_success()
    assert b.state == "closed"


def test_breaker_success_resets_failure_streak():
    b, _, _ = _breaker(threshold=3)
    b.record_failure()
    b.record_failure()
    b.record_success()  # streak broken
    b.record_failure()
    b.record_failure()
    assert b.state == "closed"


# ---------------------------------------------------------------------------
# retry policy
# ---------------------------------------------------------------------------


def test_backoff_schedule_exponential_capped():
    import random

    p = RetryPolicy(max_attempts=6, base_delay=0.1, multiplier=2.0,
                    max_delay=0.5, jitter=0.0)
    assert [p.delay(i) for i in range(5)] == [0.1, 0.2, 0.4, 0.5, 0.5]
    # jitter is deterministic under a seeded rng and bounded
    p1 = RetryPolicy(base_delay=0.1, jitter=0.5, rng=random.Random(42))
    p2 = RetryPolicy(base_delay=0.1, jitter=0.5, rng=random.Random(42))
    d1 = [p1.delay(i) for i in range(4)]
    d2 = [p2.delay(i) for i in range(4)]
    assert d1 == d2
    for i, d in enumerate(d1):
        base = min(0.1 * 2**i, 2.0)
        assert 0.5 * base <= d <= 1.5 * base


def test_retry_call_recovers_and_reports():
    calls = []
    retried = []

    def fn(attempt):
        calls.append(attempt)
        if attempt < 2:
            raise OSError("flaky")
        return "ok"

    p = RetryPolicy(max_attempts=3, base_delay=0.001, jitter=0.0)
    out = p.call(fn, retry_on=(OSError,),
                 on_retry=lambda i, e: retried.append(i))
    assert out == "ok"
    assert calls == [0, 1, 2]
    assert retried == [0, 1]


def test_retry_exhausts_and_reraises_last():
    p = RetryPolicy(max_attempts=3, base_delay=0.001, jitter=0.0)
    with pytest.raises(OSError, match="always"):
        p.call(lambda i: (_ for _ in ()).throw(OSError("always")),
               retry_on=(OSError,))


def test_retry_stops_at_deadline_budget():
    """A backoff that would overrun the deadline is skipped: the call
    fails early instead of sleeping past its budget."""
    p = RetryPolicy(max_attempts=10, base_delay=0.2, multiplier=1.0,
                    jitter=0.0)
    calls = []

    def fn(attempt):
        calls.append(attempt)
        raise OSError("down")

    t0 = time.monotonic()
    with pytest.raises(OSError):
        p.call(fn, retry_on=(OSError,), deadline=time.monotonic() + 0.3)
    elapsed = time.monotonic() - t0
    assert elapsed < 1.0  # nowhere near 10 attempts * 0.2s
    assert len(calls) <= 3


def test_retry_non_matching_error_propagates_immediately():
    calls = []

    def fn(attempt):
        calls.append(attempt)
        raise ValueError("app bug")

    p = RetryPolicy(max_attempts=5, base_delay=0.001)
    with pytest.raises(ValueError):
        p.call(fn, retry_on=(OSError,))
    assert calls == [0]


# ---------------------------------------------------------------------------
# fault registry
# ---------------------------------------------------------------------------


def test_fault_spec_grammar():
    specs = parse_specs("storage.rpc:error:0.2,dispatch.device:delay:1.0:0.01")
    assert specs[0] == FaultSpec("storage.rpc", "error", 0.2)
    assert specs[1].mode == "delay" and specs[1].param == 0.01
    for bad in ("storage.rpc", "nope:error:1.0", "storage.rpc:explode:1.0",
                "storage.rpc:error:2.0", "storage.rpc:error:x"):
        with pytest.raises(FaultSpecError):
            parse_specs(bad)


def test_fault_point_deterministic_under_seed():
    def outcomes(seed):
        reg = FaultRegistry()
        reg.install(FaultSpec("storage.rpc", "error", 0.5, seed=seed))
        out = []
        for _ in range(32):
            try:
                reg.fire("storage.rpc")
                out.append(0)
            except FaultInjected:
                out.append(1)
        return out

    a, b = outcomes(1234), outcomes(1234)
    assert a == b  # same seed, same call order → identical fault sequence
    assert 0 < sum(a) < 32  # and it actually fires sometimes, not always
    assert outcomes(99) != a or outcomes(99) == a  # different seed allowed


def test_fault_registry_inert_by_default_and_clearable():
    reg = FaultRegistry()
    assert not reg.active()
    assert reg.fire("storage.rpc") is None  # no spec → no-op
    reg.install(FaultSpec("event.insert", "error", 1.0))
    with pytest.raises(FaultInjected):
        reg.fire("event.insert")
    assert reg.fire("storage.rpc") is None  # other points unaffected
    reg.clear("event.insert")
    assert reg.fire("event.insert") is None
    reg.install(FaultSpec("event.insert", "error", 1.0))
    reg.clear()
    assert not reg.active()


def test_fault_corrupt_only_where_supported():
    reg = FaultRegistry()
    reg.install(FaultSpec("storage.rpc", "corrupt", 1.0))
    assert reg.fire("storage.rpc", corruptable=True) == "corrupt"
    with pytest.raises(FaultInjected):
        reg.fire("storage.rpc")  # site can't corrupt → injected error


def test_fault_delay_sleeps():
    reg = FaultRegistry()
    reg.install(FaultSpec("dispatch.device", "delay", 1.0, param=0.05))
    t0 = time.monotonic()
    assert reg.fire("dispatch.device") == "delay"
    assert time.monotonic() - t0 >= 0.05


def test_fault_env_configuration():
    reg = FaultRegistry()
    reg.configure_from_env({
        "PIO_FAULTS": "storage.rpc:error:0.25,model.load:delay:1.0:0.1",
        "PIO_FAULTS_SEED": "7",
    })
    specs = {s["point"]: s for s in reg.specs()}
    assert specs["storage.rpc"]["probability"] == 0.25
    assert specs["storage.rpc"]["seed"] == 7
    assert specs["model.load"]["mode"] == "delay"


# ---------------------------------------------------------------------------
# deadline plumbing
# ---------------------------------------------------------------------------


def test_deadline_header_roundtrip():
    at = deadline_mod.parse_header("250")  # 250 ms budget
    assert at is not None
    token = deadline_mod.set_deadline(at)
    try:
        rem = deadline_mod.remaining()
        assert rem is not None and 0.1 < rem <= 0.25
        assert not deadline_mod.expired()
        hv = deadline_mod.header_value()
        assert hv is not None and 0 <= int(hv) <= 250
    finally:
        deadline_mod.reset(token)
    assert deadline_mod.remaining() is None


def test_deadline_header_rejects_garbage():
    assert deadline_mod.parse_header(None) is None
    assert deadline_mod.parse_header("") is None
    assert deadline_mod.parse_header("soon") is None
    assert deadline_mod.parse_header("inf") is None


def test_deadline_expired_and_scope():
    with deadline_mod.deadline_scope(deadline_mod.from_budget(-1.0)):
        assert deadline_mod.expired()
        assert deadline_mod.header_value() == "0"  # propagates AS expired
    assert not deadline_mod.expired()


# ---------------------------------------------------------------------------
# event WAL
# ---------------------------------------------------------------------------


def _mk_event(i):
    from predictionio_tpu.data.event import Event

    return Event(event="buy", entity_type="user", entity_id=f"u{i}",
                 properties={"i": i})


def test_wal_spill_and_ordered_replay(tmp_path):
    wal = EventWAL(str(tmp_path))
    ids = [wal.append(_mk_event(i), 1, None) for i in range(5)]
    assert len(set(ids)) == 5
    assert wal.pending() == 5
    landed = []
    n, err = wal.replay(lambda e, a, c, r: landed.append((e.entity_id, a, r)))
    assert (n, err) == (5, None)
    assert [x[0] for x in landed] == [f"u{i}" for i in range(5)]  # order
    assert [x[2] for x in landed] == ids  # req_ids survive to replay
    assert wal.pending() == 0
    # fully-acked segments are reclaimed
    assert not list(tmp_path.glob("wal-*"))


def test_wal_replay_resumes_without_duplicates(tmp_path):
    """A replay pass that dies mid-segment resumes from the ack high-water
    mark: already-landed events are not re-sent (the dedupe the 'zero
    duplicates' contract rests on)."""
    wal = EventWAL(str(tmp_path))
    for i in range(6):
        wal.append(_mk_event(i), 1, None)

    landed = []

    def flaky(e, a, c, r):
        if len(landed) == 3:
            raise OSError("storage died again")
        landed.append(e.entity_id)

    n, err = wal.replay(flaky)
    assert n == 3 and isinstance(err, OSError)
    assert wal.pending() == 3
    n, err = wal.replay(lambda e, a, c, r: landed.append(e.entity_id))
    assert (n, err) == (3, None)
    assert landed == [f"u{i}" for i in range(6)]  # each exactly once
    assert wal.pending() == 0


def test_wal_crash_recovery_scans_disk(tmp_path):
    """A fresh process over the same directory picks up unreplayed
    records (zero loss across restarts)."""
    wal = EventWAL(str(tmp_path))
    for i in range(4):
        wal.append(_mk_event(i), 2, 7)
    landed = []

    def die_after_two(e, a, c, r):
        if len(landed) >= 2:
            raise OSError("down")
        landed.append((e.entity_id, a, c))

    n, err = wal.replay(die_after_two)
    assert n == 2 and err is not None
    wal.close()

    wal2 = EventWAL(str(tmp_path))  # "restart"
    assert wal2.pending() == 2
    n, err = wal2.replay(lambda e, a, c, r: landed.append((e.entity_id, a, c)))
    assert (n, err) == (2, None)
    assert [x[0] for x in landed] == ["u0", "u1", "u2", "u3"]
    assert all(a == 2 and c == 7 for _e, a, c in landed)


def test_wal_appends_during_replay_are_not_lost(tmp_path):
    wal = EventWAL(str(tmp_path))
    wal.append(_mk_event(0), 1, None)
    landed = []

    def insert(e, a, c, r):
        landed.append(e.entity_id)
        if e.entity_id == "u0":
            # a handler spills WHILE the replayer is draining
            wal.append(_mk_event(99), 1, None)

    wal.replay(insert)
    wal.replay(insert)  # next pass picks up the racing append
    assert landed == ["u0", "u99"]
    assert wal.pending() == 0


# ---------------------------------------------------------------------------
# dispatcher timeout leak (ISSUE 4 satellite regression test)
# ---------------------------------------------------------------------------


class _SlowAlgo:
    def __init__(self, dispatched, delay=0.15):
        self.dispatched = dispatched
        self.delay = delay
        self.serving_context = None

    def batch_predict(self, ctx, model, queries):
        self.dispatched.extend(q for _i, q in queries)
        time.sleep(self.delay)
        return [(i, f"p-{q}") for i, q in queries]

    def predict(self, model, q):
        self.dispatched.append(q)
        return f"p-{q}"


class _PassServing:
    def serve(self, q, preds):
        return preds[0]


class _Owner:
    def bookkeep_predict(self, *_a):
        pass

    def __init__(self):
        self.shed = []

    def count_shed(self, reason):
        self.shed.append(reason)


def test_submit_timeout_marks_cancelled_and_skips_dispatch():
    """A query whose client stopped waiting must NOT still burn a device
    dispatch: the drain loop skips cancelled entries (the old code
    dispatched them anyway)."""
    from predictionio_tpu.resilience.deadline import DeadlineExceeded
    from predictionio_tpu.workflow.server import _BatchDispatcher

    dispatched = []

    class _RT:
        algorithms = [_SlowAlgo(dispatched, delay=0.2)]
        models = [None]
        serving = _PassServing()

    owner = _Owner()
    disp = _BatchDispatcher(owner, window_ms=2.0, max_batch=8,
                            max_window_ms=30.0, pipeline_depth=1)
    try:
        rt = _RT()
        # occupy the single pipeline slot so the victim stays queued
        t = threading.Thread(target=lambda: disp.submit("warm", rt))
        t.start()
        time.sleep(0.05)
        with pytest.raises(DeadlineExceeded):
            disp.submit("victim", rt, timeout=0.05)
        t.join()
        time.sleep(0.4)  # give the drain loop time to pass the victim by
        assert "warm" in dispatched
        assert "victim" not in dispatched, (
            "cancelled query still burned a device dispatch"
        )
        assert "cancelled" in owner.shed
    finally:
        disp.stop()


def test_expired_deadline_shed_at_drain_time():
    from predictionio_tpu.resilience.deadline import DeadlineExceeded
    from predictionio_tpu.workflow.server import _BatchDispatcher

    dispatched = []

    class _RT:
        algorithms = [_SlowAlgo(dispatched, delay=0.1)]
        models = [None]
        serving = _PassServing()

    owner = _Owner()
    disp = _BatchDispatcher(owner, window_ms=2.0, max_batch=8,
                            max_window_ms=30.0, pipeline_depth=1)
    try:
        rt = _RT()
        t = threading.Thread(target=lambda: disp.submit("warm", rt))
        t.start()
        time.sleep(0.03)
        # already-expired deadline: the waiter gets DeadlineExceeded, and
        # the device never sees the query. The shed reason depends on
        # who noticed first (the abandoning waiter marks `cancelled`, the
        # drain loop checks the deadline) — both are correct sheds.
        with pytest.raises(DeadlineExceeded):
            disp.submit("expired", rt, deadline=time.monotonic() - 0.01)
        t.join()
        time.sleep(0.3)  # let the drain loop pass the dead entry by
        assert "expired" not in dispatched
        assert owner.shed and set(owner.shed) <= {
            "cancelled", "expired_in_queue"
        }
    finally:
        disp.stop()


# ---------------------------------------------------------------------------
# code-review regressions: probe release, WAL restart ordering, daemon shed
# ---------------------------------------------------------------------------


def test_breaker_release_probe_unwedges_half_open():
    """An allowed call that aborts WITHOUT an endpoint verdict (local
    deadline expiry, parse error) must free the half-open probe slot —
    otherwise the breaker stays fail-fast forever."""
    b, clock, _ = _breaker(threshold=1, cooldown=1.0)
    b.record_failure()
    clock.advance(1.1)
    assert b.allow()  # probe claimed ...
    b.release_probe()  # ... but the attempt aborted locally
    assert b.allow()  # the NEXT caller can still probe
    b.record_success()
    assert b.state == "closed"


def test_client_deadline_expiry_does_not_wedge_breaker(tmp_path):
    """RemoteClient: DeadlineExceeded raised between allow() and the
    network attempt releases the probe, so recovery still happens."""
    from predictionio_tpu.data.api.storage_server import StorageServer
    from predictionio_tpu.data.storage.registry import (
        SourceConfig, Storage, StorageConfig,
    )
    from predictionio_tpu.data.storage.remote import RemoteEventStore
    from predictionio_tpu.resilience.breaker import reset_breakers

    reset_breakers()
    try:
        cfg = StorageConfig(
            sources={"S": SourceConfig(
                "S", "sqlite", {"PATH": str(tmp_path / "p.db")}
            )},
            repositories={
                "METADATA": "S", "EVENTDATA": "S", "MODELDATA": "S",
            },
        )
        daemon = StorageServer(
            Storage(cfg), host="127.0.0.1", port=0
        ).start()
        store = RemoteEventStore({
            "HOST": "127.0.0.1", "PORT": str(daemon.port),
            "RETRY_ATTEMPTS": "1", "BREAKER_THRESHOLD": "1",
            "BREAKER_COOLDOWN": "0.0",
        })
        breaker = store._client.breaker_for("events")
        # trip the breaker with an injected outage
        faults_mod.install(
            faults_mod.FaultSpec("storage.rpc", "error", 1.0)
        )
        try:
            with pytest.raises(Exception):
                store.init_app(1)
        finally:
            faults_mod.clear()
        assert breaker.state in ("open", "half_open")
        # cooldown 0: next call is the probe — but its deadline already
        # expired, so it aborts before any I/O
        with deadline_mod.deadline_scope(deadline_mod.from_budget(-1.0)):
            with pytest.raises(deadline_mod.DeadlineExceeded):
                store.init_app(1)
        # the probe slot was released: a healthy call recovers the breaker
        assert store.init_app(1) is True
        assert breaker.state == "closed"
        daemon.shutdown()
    finally:
        reset_breakers()


def test_wal_replay_order_across_restarts(tmp_path):
    """Segments from an older process replay before a newer process's —
    the epoch-ms name prefix keys the sort, not the pid."""
    wal1 = EventWAL(str(tmp_path))
    wal1.append(_mk_event(1), 1, None)
    wal1.close()
    time.sleep(0.01)  # ensure a later ms stamp for the "restart"
    wal2 = EventWAL(str(tmp_path))  # fresh process over the same dir
    wal2.append(_mk_event(2), 1, None)
    assert wal2.pending() == 2
    landed = []
    n, err = wal2.replay(lambda e, a, c, r: landed.append(e.entity_id))
    assert (n, err) == (2, None)
    assert landed == ["u1", "u2"]  # arrival order, not name-shape order


def test_daemon_sheds_expired_rpc_as_deadline(tmp_path):
    """An RPC arriving with an expired X-PIO-Deadline is shed by the
    daemon with shed=true, which the client maps to DeadlineExceeded
    (not a generic StorageError → 500)."""
    import http.client
    import json as _json

    from predictionio_tpu.data.api.storage_server import StorageServer
    from predictionio_tpu.data.storage.registry import (
        SourceConfig, Storage, StorageConfig,
    )

    cfg = StorageConfig(
        sources={"S": SourceConfig(
            "S", "sqlite", {"PATH": str(tmp_path / "d.db")}
        )},
        repositories={"METADATA": "S", "EVENTDATA": "S", "MODELDATA": "S"},
    )
    daemon = StorageServer(Storage(cfg), host="127.0.0.1", port=0).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", daemon.port, timeout=5)
        body = _json.dumps({
            "dao": "events", "method": "init_app", "args": [1], "kwargs": {},
        }).encode()
        conn.request("POST", "/rpc", body=body, headers={
            "Content-Type": "application/json", "X-PIO-Deadline": "0",
        })
        payload = _json.loads(conn.getresponse().read())
        conn.close()
        assert payload["ok"] is False and payload.get("shed") is True
    finally:
        daemon.shutdown()


def test_fault_admin_validates_before_clearing(tmp_path):
    """POST /debug/faults with a malformed `set` must not have executed
    the `clear` — config swaps are atomic-or-rejected."""
    import json as _json
    import urllib.error
    import urllib.request

    from predictionio_tpu.tools.dashboard import Dashboard
    from predictionio_tpu.data.storage.registry import (
        SourceConfig, Storage, StorageConfig,
    )

    cfg = StorageConfig(
        sources={"S": SourceConfig(
            "S", "sqlite", {"PATH": str(tmp_path / "f.db")}
        )},
        repositories={"METADATA": "S", "EVENTDATA": "S", "MODELDATA": "S"},
    )
    d = Dashboard(Storage(cfg), ip="127.0.0.1", port=0)
    port = d.start()
    import os as _os

    _os.environ["PIO_FAULTS_ADMIN"] = "1"
    try:
        faults_mod.install(faults_mod.FaultSpec("model.load", "error", 1.0))
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/debug/faults",
            data=_json.dumps({
                "clear": True, "set": "storage.rpc:error:2.0",  # prob > 1
            }).encode(),
            method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 400
        # the pre-existing spec survived the rejected request
        assert {s["point"] for s in faults_mod.specs()} == {"model.load"}
    finally:
        _os.environ.pop("PIO_FAULTS_ADMIN", None)
        faults_mod.clear()
        d.stop()


def test_per_dao_breakers_isolate_events_outage(tmp_path):
    """ISSUE 15 satellite (carried PR-4 follow-up): breakers key by
    endpoint+DAO — an open EVENTS breaker fails only the events path
    fast, while the metadata DAOs on the same daemon keep answering."""
    from predictionio_tpu.data.api.storage_server import StorageServer
    from predictionio_tpu.data.storage.base import (
        App,
        StorageCircuitOpenError,
    )
    from predictionio_tpu.data.storage.registry import (
        SourceConfig, Storage, StorageConfig,
    )
    from predictionio_tpu.obs.registry import get_default_registry
    from predictionio_tpu.resilience.breaker import reset_breakers

    reset_breakers()
    try:
        cfg = StorageConfig(
            sources={"S": SourceConfig(
                "S", "sqlite", {"PATH": str(tmp_path / "dao.db")}
            )},
            repositories={
                "METADATA": "S", "EVENTDATA": "S", "MODELDATA": "S",
            },
        )
        daemon = StorageServer(
            Storage(cfg), host="127.0.0.1", port=0
        ).start()
        remote_cfg = StorageConfig(
            sources={"R": SourceConfig("R", "remote", {
                "HOST": "127.0.0.1", "PORT": str(daemon.port),
                "RETRY_ATTEMPTS": "1", "BREAKER_THRESHOLD": "2",
                "BREAKER_COOLDOWN": "60",
            })},
            repositories={
                "METADATA": "R", "EVENTDATA": "R", "MODELDATA": "R",
            },
        )
        storage = Storage(remote_cfg)
        apps = storage.get_meta_data_apps()
        events = storage.get_events()
        app_id = apps.insert(App(0, "daoapp"))
        events.init_app(app_id)

        client = events._client
        ev_breaker = client.breaker_for("events")
        meta_breaker = client.breaker_for("apps")
        assert ev_breaker is not meta_breaker

        # trip ONLY the events breaker (the split under test: the old
        # process-global per-endpoint breaker would have opened both)
        ev_breaker.record_failure()
        ev_breaker.record_failure()
        assert ev_breaker.state == "open"

        with pytest.raises(StorageCircuitOpenError):
            events.init_app(app_id)
        # ...while the metadata path on the SAME daemon still serves
        assert apps.get(app_id).name == "daoapp"
        assert meta_breaker.state == "closed"

        # the state gauge carries the dao dimension
        gauge = get_default_registry().gauge(
            "resilience_breaker_state", "", ("endpoint", "dao")
        )
        ep = f"storage:127.0.0.1:{daemon.port}"
        assert gauge.value(endpoint=f"{ep}/events", dao="events") == 1.0
        assert gauge.value(endpoint=f"{ep}/apps", dao="apps") == 0.0
        daemon.shutdown()
    finally:
        reset_breakers()
