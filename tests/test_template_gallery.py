"""Template gallery e2e (VERDICT r1 #8): scaffold → import events →
train → deploy → query, all through bin/pio as an operator would."""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.request
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
PIO = str(REPO / "bin" / "pio")


def run_pio(args, cwd, env, timeout=180):
    out = subprocess.run(
        [PIO, *args], cwd=cwd, env=env,
        capture_output=True, text=True, timeout=timeout,
    )
    assert out.returncode == 0, (
        f"pio {' '.join(args)} failed:\n{out.stdout}\n{out.stderr}"
    )
    return out.stdout


@pytest.fixture()
def workdir(tmp_path):
    env = dict(os.environ)
    env["PIO_FS_BASEDIR"] = str(tmp_path / "store")
    env.pop("PIO_STORAGE_REPOSITORIES_METADATA_SOURCE", None)
    return tmp_path, env


def test_template_list(workdir):
    tmp, env = workdir
    out = run_pio(["template", "list"], tmp, env)
    for name in (
        "recommendation", "similarproduct", "classification",
        "ecommerce", "universal", "markov", "itemsim",
        "simrank", "friendrec",
    ):
        assert name in out


def test_scaffold_refuses_overwrite(workdir):
    tmp, env = workdir
    run_pio(["template", "get", "classification", str(tmp / "eng")], tmp, env)
    out = subprocess.run(
        [PIO, "template", "get", "classification", str(tmp / "eng")],
        cwd=tmp, env=env, capture_output=True, text=True,
    )
    assert out.returncode != 0
    assert "already contains" in out.stdout + out.stderr


def test_scaffolded_engine_trains_and_deploys(workdir):
    tmp, env = workdir
    eng_dir = tmp / "myengine"
    run_pio(
        ["template", "get", "recommendation", str(eng_dir),
         "--package", "shoprec"],
        tmp, env,
    )
    # engine.json points at the scaffolded package, not the built-in
    variant = json.loads((eng_dir / "engine.json").read_text())
    assert variant["engineFactory"] == "shoprec.RecommendationEngine"
    # wire the app name and create the app + events
    variant["datasource"]["params"]["app_name"] = "ShopApp"
    (eng_dir / "engine.json").write_text(json.dumps(variant))
    run_pio(["app", "new", "ShopApp"], eng_dir, env)
    lines = []
    for u in range(6):
        for i in range(5):
            if (u + i) % 2 == 0:
                lines.append(json.dumps({
                    "event": "rate", "entityType": "user",
                    "entityId": f"u{u}", "targetEntityType": "item",
                    "targetEntityId": f"i{i}",
                    "properties": {"rating": 4.0},
                    "eventTime": "2026-01-01T00:00:00.000Z",
                }))
    (eng_dir / "events.jsonl").write_text("\n".join(lines) + "\n")
    run_pio(["import", "--app", "ShopApp", "--input", "events.jsonl"],
            eng_dir, env)

    out = run_pio(["train", "--engine-json", "engine.json"], eng_dir, env)
    assert "completed" in out.lower()

    # deploy on an ephemeral port and query it
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.Popen(
        [PIO, "deploy", "--engine-json", "engine.json",
         "--ip", "127.0.0.1", "--port", str(port)],
        cwd=eng_dir, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        deadline = time.time() + 120
        body = None
        while time.time() < deadline:
            try:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/queries.json",
                    data=json.dumps({"user": "u0", "num": 2}).encode(),
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=5) as r:
                    body = json.loads(r.read())
                break
            except OSError:
                assert proc.poll() is None, (
                    "deploy died:\n" + proc.stdout.read()
                )
                time.sleep(0.5)
        assert body is not None, "deploy server never answered"
        assert len(body["item_scores"]) == 2
    finally:
        proc.terminate()
        proc.wait(timeout=15)
