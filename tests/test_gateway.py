"""Gateway unit tests (ISSUE 15): consistent-hash ring, replica
registry + durable identity, routing/bounded-load/hedge/failover
against in-process stub replicas, sticky canary bucket forwarding,
autoscaler policy, and the per-replica online-cursor regression."""

import json
import time
import urllib.error
import urllib.request

import pytest

from predictionio_tpu.data.storage.registry import (
    SourceConfig,
    Storage,
    StorageConfig,
)
from predictionio_tpu.gateway import (
    Autoscaler,
    AutoscalerConfig,
    GatewayConfig,
    GatewayServer,
    HashRing,
    ReplicaConfig,
    ReplicaInfo,
    ReplicaMember,
    ReplicaRegistry,
    replica_identity,
)
from predictionio_tpu.gateway.replica_main import stub_runtime
from predictionio_tpu.workflow.server import QueryServer, QueryServerConfig


def _memory_storage() -> Storage:
    return Storage(StorageConfig(
        sources={"M": SourceConfig("M", "memory", {})},
        repositories={
            "METADATA": "M", "EVENTDATA": "M", "MODELDATA": "M",
        },
    ))


def _post(port, path, body, headers=None, timeout=15):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode() or "null"), dict(e.headers)


def _get(port, path, timeout=10):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout
    ) as r:
        return r.status, json.loads(r.read().decode())


# ---------------------------------------------------------------------------
# consistent-hash ring
# ---------------------------------------------------------------------------


class TestHashRing:
    def test_ordered_is_a_permutation(self):
        ring = HashRing(["a", "b", "c", "d"])
        order = ring.ordered("tenant-42")
        assert sorted(order) == ["a", "b", "c", "d"]
        # deterministic
        assert ring.ordered("tenant-42") == order

    def test_membership_change_remaps_minimally(self):
        """Removing one replica must only remap the keys it owned —
        the property the tenant model cache depends on."""
        full = HashRing(["a", "b", "c", "d"])
        less = HashRing(["a", "b", "c"])
        keys = [f"k{i}" for i in range(500)]
        moved = sum(
            1 for k in keys
            if full.owner(k) != "d" and full.owner(k) != less.owner(k)
        )
        assert moved == 0, "keys not owned by the removed replica moved"

    def test_distribution_is_roughly_even(self):
        ring = HashRing(["a", "b", "c"], vnodes=64)
        from collections import Counter

        counts = Counter(ring.owner(f"k{i}") for i in range(3000))
        assert set(counts) == {"a", "b", "c"}
        assert min(counts.values()) > 500  # no starved replica

    def test_empty_ring(self):
        assert HashRing([]).ordered("x") == []
        assert HashRing([]).owner("x") is None


# ---------------------------------------------------------------------------
# replica registry + durable identity
# ---------------------------------------------------------------------------


class TestReplicaRegistry:
    def test_upsert_heartbeat_live_stale_gc(self):
        reg = ReplicaRegistry(_memory_storage())
        reg.upsert(ReplicaInfo(
            id="r1", url="http://h:1", heartbeat_at=time.time(),
            engines=["als"], serve_dtype="int8",
        ))
        reg.upsert(ReplicaInfo(
            id="r2", url="http://h:2", heartbeat_at=time.time() - 3600,
        ))
        assert {r.id for r in reg.list()} == {"r1", "r2"}
        assert [r.id for r in reg.live(stale_after_s=5)] == ["r1"]
        got = reg.get("r1")
        assert got.serve_dtype == "int8" and got.engines == ["als"]
        assert reg.gc(stale_after_s=60) == ["r2"]
        assert {r.id for r in reg.list()} == {"r1"}

    def test_heartbeat_compacts_to_one_live_event(self):
        storage = _memory_storage()
        reg = ReplicaRegistry(storage)
        reg.upsert(ReplicaInfo(id="r1", url="http://h:1"))
        prev = None
        for _ in range(10):
            prev = reg.heartbeat("r1", prev, inflight=3)
        from predictionio_tpu.gateway.registry import REPLICA_ENTITY

        events = reg._store.events(REPLICA_ENTITY, "r1")
        assert len(events) <= 2  # initial upsert + one live beat
        got = reg.get("r1")
        assert got.inflight == 3 and got.url == "http://h:1"

    def test_draining_flag_survives_heartbeats(self):
        reg = ReplicaRegistry(_memory_storage())
        reg.upsert(ReplicaInfo(id="r1", url="http://h:1"))
        reg.set_draining("r1", True)
        prev = reg.heartbeat("r1", None, inflight=0)
        reg.heartbeat("r1", prev, inflight=0)
        assert reg.get("r1").draining is True

    def test_replica_identity_is_durable(self, tmp_path):
        d1, d2 = str(tmp_path / "a"), str(tmp_path / "b")
        rid = replica_identity(d1)
        assert rid.startswith("replica-")
        assert replica_identity(d1) == rid  # restart resumes the SAME id
        assert replica_identity(d2) != rid  # second replica differs


# ---------------------------------------------------------------------------
# sticky canary bucket forwarding
# ---------------------------------------------------------------------------


class TestStickyBucket:
    def test_bucket_overrides_local_hash(self):
        from predictionio_tpu.deploy.rollout import (
            route_bucket,
            sticky_candidate,
        )

        raw = b'{"user": "u1"}'
        local = sticky_candidate(raw, 0.5)
        assert sticky_candidate(raw, 0.5, bucket=route_bucket(raw)) == local
        # forced buckets pick the variant regardless of the body
        assert sticky_candidate(raw, 0.5, bucket=0) is True
        assert sticky_candidate(raw, 0.5, bucket=9999) is False

    def test_pick_runtime_honors_gateway_bucket(self):
        """The replica's canary decision must follow the forwarded
        bucket, not its own hash — a hedged retry landing on another
        replica gets the same variant."""
        from types import SimpleNamespace

        storage = _memory_storage()
        srv = QueryServer(
            storage, stub_runtime("r1"),
            QueryServerConfig(ip="127.0.0.1", port=0, micro_batch=False),
        )
        candidate = stub_runtime("r1-candidate")
        srv.candidate = candidate
        srv.rollout = SimpleNamespace(
            config=SimpleNamespace(shadow=False, fraction=0.5),
            st=SimpleNamespace(state="canary"),
        )
        raw = b'{"q": 1}'
        rt_low, variant_low = srv.pick_runtime(raw, bucket=0)
        rt_high, variant_high = srv.pick_runtime(raw, bucket=9999)
        assert (variant_low, variant_high) == ("candidate", "live")
        assert rt_low is candidate and rt_high is srv.runtime

    def test_route_hash_header_parsed_end_to_end(self):
        """POST with X-PIO-Route-Hash reaches pick_runtime as the
        bucket (captured via a spy)."""
        storage = _memory_storage()
        srv = QueryServer(
            storage, stub_runtime("r1"),
            QueryServerConfig(ip="127.0.0.1", port=0, micro_batch=False),
        )
        seen = []
        original = srv.pick_runtime

        def spy(raw, bucket=None):
            seen.append(bucket)
            return original(raw, bucket=bucket)

        srv.pick_runtime = spy
        port = srv.start()
        try:
            status, _, _ = _post(
                port, "/queries.json", {"q": 1},
                headers={"X-PIO-Route-Hash": "1234"},
            )
            assert status == 200
            status, _, _ = _post(port, "/queries.json", {"q": 2})
            assert status == 200
        finally:
            srv.stop()
        assert seen == [1234, None]


# ---------------------------------------------------------------------------
# gateway routing against in-process stub replicas
# ---------------------------------------------------------------------------


@pytest.fixture()
def fleet():
    """3 stub replicas + a gateway on shared memory storage. Yields
    (gateway, gateway_port, replicas: list[QueryServer], storage)."""
    storage = _memory_storage()
    replicas = []
    for i in range(3):
        rid = f"r{i}"
        srv = QueryServer(
            storage, stub_runtime(rid),
            QueryServerConfig(ip="127.0.0.1", port=0, micro_batch=False),
        )
        srv.start()
        srv.attach_replica(ReplicaMember(storage, srv, ReplicaConfig(
            replica_id=rid, url=f"http://127.0.0.1:{srv.port}",
            heartbeat_interval_s=0.2,
        )))
        replicas.append(srv)
    gw = GatewayServer(storage, GatewayConfig(
        ip="127.0.0.1", port=0, sync_interval_s=0.15,
        replica_stale_after_s=2.0, scrape=False,
        hedge=True, hedge_min_ms=60.0,
        breaker_threshold=2, breaker_cooldown_s=0.3,
    ))
    gport = gw.start()
    yield gw, gport, replicas, storage
    gw.stop()
    for srv in replicas:
        srv.stop()


class TestGatewayRouting:
    def test_routes_to_all_replicas_and_forwards_bucket(self, fleet):
        gw, gport, replicas, _storage = fleet
        seen = set()
        for i in range(30):
            status, body, _ = _post(gport, "/queries.json", {"q": i})
            assert status == 200
            seen.add(body["replica"])
        assert seen == {"r0", "r1", "r2"}
        status, st = _get(gport, "/gateway/status")
        assert status == 200 and st["routable"] == 3

    def test_same_body_is_sticky(self, fleet):
        _gw, gport, _replicas, _storage = fleet
        who = {
            _post(gport, "/queries.json", {"q": "fixed"})[1]["replica"]
            for _ in range(8)
        }
        assert len(who) == 1  # crc32 bucket → same ring key every time

    def test_failover_absorbs_dead_replica(self, fleet):
        """A registered-but-dead replica (fresh heartbeat, closed port)
        costs failovers, never client errors; its breaker opens and it
        is ejected."""
        gw, gport, _replicas, storage = fleet
        ReplicaRegistry(storage).upsert(ReplicaInfo(
            id="rdead", url="http://127.0.0.1:1",
            heartbeat_at=time.time() + 3600,
        ))
        gw.sync_once()
        for i in range(40):
            status, _body, _ = _post(
                gport, "/queries.json", {"q": i},
                headers={"X-PIO-Deadline": "8000"},
            )
            assert status == 200
        gw.sync_once()
        _s, st = _get(gport, "/gateway/status")
        dead = next(r for r in st["replicas"] if r["id"] == "rdead")
        assert not dead["routable"]
        assert any(
            reason.startswith("breaker_")
            for reason in dead["eject_reasons"]
        )
        assert gw._failovers.value() >= 1

    def test_stale_heartbeat_ejects_and_fresh_readmits(self, fleet):
        gw, _gport, replicas, _storage = fleet
        victim = replicas[0]
        member = victim.replica
        # freeze heartbeats (the SIGSTOP'd-process shape)
        member._stop.set()
        member._hb_thread.join()
        member._hb_thread = None
        deadline = time.time() + 10
        while time.time() < deadline:
            gw.sync_once()
            _ring, states = gw._route_snapshot()
            if not states["r0"].routable():
                break
            time.sleep(0.2)
        _ring, states = gw._route_snapshot()
        assert not states["r0"].routable()
        assert "stale_heartbeat" in states["r0"].eject_reasons()
        # heartbeats resume → re-admitted
        member._stop.clear()
        import threading

        member._hb_thread = threading.Thread(
            target=member._hb_loop, name="replica-heartbeat", daemon=True
        )
        member._hb_thread.start()
        deadline = time.time() + 10
        while time.time() < deadline:
            gw.sync_once()
            _ring, states = gw._route_snapshot()
            if states["r0"].routable():
                break
            time.sleep(0.2)
        assert states["r0"].routable()

    def test_hedge_beats_straggler(self, fleet):
        """A query stuck on a slow REPLICA is answered by the hedge on
        the next replica long before the straggler finishes. The
        straggler is replica-side (every query on r-slow sleeps), so
        the hedged copy of the SAME body is fast elsewhere."""
        gw, gport, _replicas, _storage = fleet
        slow = _replicas[0]
        # make replica r0 slow for every query it serves
        slow.runtime.algorithms[0].slow_every = 1
        slow.runtime.algorithms[0].slow_ms = 3000.0
        # find a body whose PRIMARY is the slow replica
        import zlib

        body = None
        for i in range(2000):
            cand_body = {"q": f"probe-{i}"}
            raw = json.dumps(cand_body).encode()
            key = f"q{zlib.crc32(raw) % 10000}"
            if gw.candidates(key) and gw.candidates(key)[0] == "r0":
                body = cand_body
                break
        assert body is not None
        t0 = time.perf_counter()
        req = urllib.request.Request(
            f"http://127.0.0.1:{gport}/queries.json",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json",
                     "X-PIO-Deadline": "10000"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=20) as r:
            answer = json.loads(r.read().decode())
        elapsed = time.perf_counter() - t0
        assert answer["replica"] != "r0"  # the hedge won
        assert elapsed < 2.5, (
            f"hedge did not rescue the straggler ({elapsed:.2f}s)"
        )
        assert gw._hedges.value(outcome="sent") >= 1
        assert gw._hedges.value(outcome="won") >= 1

    def test_deadline_expired_is_shed_at_gateway(self, fleet):
        _gw, gport, _replicas, _storage = fleet
        status, body, headers = _post(
            gport, "/queries.json", {"q": 1},
            headers={"X-PIO-Deadline": "0"},
        )
        assert status == 503
        assert headers.get("Retry-After") == "1"
        assert "shed" in body["message"]

    def test_no_replica_503(self):
        storage = _memory_storage()
        gw = GatewayServer(storage, GatewayConfig(
            ip="127.0.0.1", port=0, sync_interval_s=30, scrape=False,
        ))
        gport = gw.start()
        try:
            status, body, headers = _post(gport, "/queries.json", {"q": 1})
            assert status == 503
            assert "no routable replica" in body["message"]
            assert headers.get("Retry-After") == "1"
        finally:
            gw.stop()

    def test_drain_flag_stops_routing(self, fleet):
        gw, gport, replicas, storage = fleet
        ReplicaRegistry(storage).set_draining("r1", True)
        deadline = time.time() + 10
        while time.time() < deadline:
            gw.sync_once()
            _ring, states = gw._route_snapshot()
            if not states["r1"].routable():
                break
            time.sleep(0.1)
        assert "draining" in states["r1"].eject_reasons()
        for i in range(20):
            status, body, _ = _post(gport, "/queries.json", {"q": i})
            assert status == 200
            assert body["replica"] != "r1"

    def test_bounded_load_spills_hot_key(self, fleet):
        """With the sticky owner saturated past factor x mean load,
        the key's traffic spills to the next replica on the ring."""
        gw, _gport, _replicas, _storage = fleet
        key = "tenant-hot"
        _ring, states = gw._route_snapshot()
        first = gw.candidates(key)[0]
        # saturate the sticky owner
        for _ in range(50):
            states[first].enter()
        try:
            spilled = gw.candidates(key)
            assert spilled[0] != first
            assert first in spilled  # still a failover target, demoted
        finally:
            for _ in range(50):
                states[first].exit(None)


# ---------------------------------------------------------------------------
# autoscaler policy
# ---------------------------------------------------------------------------


class _FakeManager:
    def __init__(self):
        self.spawned = 0
        self.drained = []

    def spawn(self):
        self.spawned += 1
        return f"spawn-{self.spawned}"

    def drain(self, replica_id, url):
        self.drained.append(replica_id)
        return True

    def stop(self):
        pass


class TestAutoscaler:
    def _scaler(self, **cfg):
        from predictionio_tpu.obs.registry import MetricsRegistry

        class Clock:
            t = 1000.0

            def __call__(self):
                return self.t

        clock = Clock()
        mgr = _FakeManager()
        scaler = Autoscaler(
            mgr,
            AutoscalerConfig(**cfg),
            registry=MetricsRegistry(),
            clock=clock,
        )
        return scaler, mgr, clock

    def test_min_replicas_floor_spawns_even_in_cooldown(self):
        scaler, mgr, clock = self._scaler(
            min_replicas=2, cooldown_s=60, floor_boot_grace_s=5,
        )
        d = scaler.evaluate(replicas=1, mean_inflight=0.0, burn=None)
        assert d.action == "spawn" and mgr.spawned == 1
        # the freshly-spawned replica is still booting: re-firing the
        # floor every evaluation pass would be a process storm
        assert scaler.evaluate(replicas=1, mean_inflight=0.0, burn=None) is None
        # ... but the 60 s cooldown does NOT delay recovering the
        # floor — only the short boot grace does
        clock.t += 6
        d = scaler.evaluate(replicas=1, mean_inflight=0.0, burn=None)
        assert d.action == "spawn" and mgr.spawned == 2

    def test_burn_triggers_spawn_and_cooldown_holds(self):
        scaler, mgr, clock = self._scaler(
            min_replicas=1, max_replicas=4, cooldown_s=30,
            scale_up_burn=14.4,
        )
        d = scaler.evaluate(replicas=2, mean_inflight=1.0, burn=20.0)
        assert d.action == "spawn" and "burn" in d.reason
        assert scaler.evaluate(replicas=2, mean_inflight=1.0, burn=20.0) is None
        clock.t += 31
        d = scaler.evaluate(replicas=3, mean_inflight=1.0, burn=20.0)
        assert d.action == "spawn" and mgr.spawned == 2

    def test_saturation_triggers_spawn_max_rail_holds(self):
        scaler, mgr, clock = self._scaler(
            min_replicas=1, max_replicas=2, target_inflight=8,
            cooldown_s=0,
        )
        d = scaler.evaluate(replicas=1, mean_inflight=9.0, burn=None)
        assert d.action == "spawn"
        clock.t += 1
        assert scaler.evaluate(replicas=2, mean_inflight=9.0, burn=None) is None

    def test_idle_drains_least_loaded(self):
        scaler, mgr, clock = self._scaler(
            min_replicas=1, target_inflight=8, cooldown_s=0,
            scale_down_fraction=0.25,
        )
        d = scaler.evaluate(
            replicas=3, mean_inflight=0.5, burn=0.1,
            drain_candidate=("r2", "http://h:2"),
        )
        assert d.action == "drain" and d.target == "r2"
        assert mgr.drained == ["r2"]

    def test_decisions_land_on_log_and_counter(self):
        scaler, mgr, clock = self._scaler(min_replicas=1, cooldown_s=0)
        scaler.evaluate(replicas=0, mean_inflight=0, burn=None)
        st = scaler.status()
        assert st["decisions"][-1]["action"] == "spawn"
        assert scaler._events.value(action="spawn") == 1


# ---------------------------------------------------------------------------
# per-replica online cursor identity (the acceptance regression)
# ---------------------------------------------------------------------------


class TestReplicaCursorIdentity:
    def test_two_replicas_get_distinct_default_cursors(self, tmp_path):
        """The PR-9 caveat made automatic: two replicas folding the
        same app's stream derive DISTINCT durable cursor records from
        their replica identity — no shared-cursor double-fold."""
        storage = _memory_storage()
        cursor_ids = []
        for name in ("a", "b"):
            srv = QueryServer(
                storage, stub_runtime(name),
                QueryServerConfig(
                    ip="127.0.0.1", port=0, micro_batch=False
                ),
            )
            srv.start()
            srv.attach_replica(ReplicaMember(storage, srv, ReplicaConfig(
                state_dir=str(tmp_path / name),
                url=f"http://127.0.0.1:{srv.port}",
                heartbeat_interval_s=30,
            )))
            consumer = srv.attach_online(app_id=1)
            cursor_ids.append(consumer.cursor_id)
            rid = srv.replica.replica_id
            assert rid in consumer.cursor_id, (
                "cursor name must carry the durable replica id"
            )
            srv.stop()
        assert cursor_ids[0] != cursor_ids[1], (
            "two replicas would share one single-writer cursor record"
        )
        # restart of replica "a" resumes the SAME cursor (durability)
        srv = QueryServer(
            storage, stub_runtime("a2"),
            QueryServerConfig(ip="127.0.0.1", port=0, micro_batch=False),
        )
        srv.start()
        srv.attach_replica(ReplicaMember(storage, srv, ReplicaConfig(
            state_dir=str(tmp_path / "a"),
            url=f"http://127.0.0.1:{srv.port}",
            heartbeat_interval_s=30,
        )))
        consumer = srv.attach_online(app_id=1)
        assert consumer.cursor_id == cursor_ids[0]
        srv.stop()

    def test_explicit_cursor_name_still_wins(self):
        from predictionio_tpu.online import OnlineConsumerConfig

        storage = _memory_storage()
        srv = QueryServer(
            storage, stub_runtime("a"),
            QueryServerConfig(ip="127.0.0.1", port=0, micro_batch=False),
        )
        srv.start()
        srv.attach_replica(ReplicaMember(storage, srv, ReplicaConfig(
            replica_id="rX", url=f"http://127.0.0.1:{srv.port}",
            heartbeat_interval_s=30,
        )))
        consumer = srv.attach_online(
            app_id=1, config=OnlineConsumerConfig(name="custom/cursor")
        )
        assert consumer.cursor_id == "custom/cursor"
        srv.stop()


# ---------------------------------------------------------------------------
# replica endpoints
# ---------------------------------------------------------------------------


class TestReplicaEndpoints:
    def test_health_and_replica_status(self):
        storage = _memory_storage()
        srv = QueryServer(
            storage, stub_runtime("r1"),
            QueryServerConfig(ip="127.0.0.1", port=0, micro_batch=False),
        )
        port = srv.start()
        try:
            status, body = _get(port, "/health")
            assert status == 200 and body["status"] == "alive"
            status, body = _get(port, "/replica/status")
            assert status == 200 and body["state"] == "detached"
            srv.attach_replica(ReplicaMember(storage, srv, ReplicaConfig(
                replica_id="r1", url=f"http://127.0.0.1:{port}",
                heartbeat_interval_s=30,
            )))
            status, body = _get(port, "/replica/status")
            assert body["state"] == "attached"
            assert body["replica_id"] == "r1"
        finally:
            srv.stop()

    def test_prefetch_endpoint_without_tenancy_accepts_nothing(self):
        storage = _memory_storage()
        srv = QueryServer(
            storage, stub_runtime("r1"),
            QueryServerConfig(ip="127.0.0.1", port=0, micro_batch=False),
        )
        port = srv.start()
        try:
            status, body, _ = _post(
                port, "/replica/prefetch", {"tenants": ["t1", "t2"]}
            )
            assert status == 200 and body["accepted"] == []
            status, _body, _ = _post(
                port, "/replica/prefetch", {"tenants": "nope"}
            )
            assert status == 400
        finally:
            srv.stop()

    def test_drain_endpoint_finishes_inflight_then_stops(self):
        storage = _memory_storage()
        srv = QueryServer(
            storage, stub_runtime("r1"),
            QueryServerConfig(ip="127.0.0.1", port=0, micro_batch=False),
        )
        port = srv.start()
        member = ReplicaMember(storage, srv, ReplicaConfig(
            replica_id="r1", url=f"http://127.0.0.1:{port}",
            heartbeat_interval_s=0.2, drain_grace_s=0.05,
        ))
        srv.attach_replica(member)
        # a slow in-flight query rides out the drain
        import threading

        results = []

        def slow_query():
            results.append(_post(
                port, "/queries.json", {"q": 1, "sleep_ms": 600},
                timeout=20,
            ))

        t = threading.Thread(target=slow_query, daemon=True)
        t.start()
        time.sleep(0.15)  # let it arrive
        status, body, _ = _post(port, "/replica/drain", {})
        assert status == 202 and body["draining"] is True
        status2, _body2, _ = _post(port, "/replica/drain", {})
        assert status2 == 409  # already draining
        t.join(timeout=20)
        assert results and results[0][0] == 200, (
            "in-flight query was dropped by the drain"
        )
        # the drain thread stops the server
        deadline = time.time() + 10
        while time.time() < deadline and srv._server is not None:
            time.sleep(0.1)
        assert srv._server is None
        # record removed on clean retirement
        assert ReplicaRegistry(storage).get("r1") is None
