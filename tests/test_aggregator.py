"""$set/$unset/$delete fold semantics
(reference: LEventAggregatorSpec / PEventAggregatorSpec)."""

import datetime as dt

from predictionio_tpu.data import DataMap, Event
from predictionio_tpu.data.aggregator import (
    EventOp,
    aggregate_properties,
    aggregate_properties_of_entity,
)

UTC = dt.timezone.utc


def T(i: int) -> dt.datetime:
    return dt.datetime(2024, 1, 1, tzinfo=UTC) + dt.timedelta(minutes=i)


def ev(name, eid, props=None, t=0):
    return Event(
        event=name,
        entity_type="user",
        entity_id=eid,
        properties=DataMap(props or {}),
        event_time=T(t),
    )


class TestAggregation:
    def test_set_merge_last_write_wins(self):
        events = [
            ev("$set", "u1", {"a": 1, "b": 2}, t=0),
            ev("$set", "u1", {"b": 3, "c": 4}, t=1),
        ]
        result = aggregate_properties(events)
        pm = result["u1"]
        assert pm.to_dict() == {"a": 1, "b": 3, "c": 4}
        assert pm.first_updated == T(0)
        assert pm.last_updated == T(1)

    def test_out_of_order_set(self):
        # older $set arriving later must not clobber newer value
        events = [
            ev("$set", "u1", {"a": "new"}, t=5),
            ev("$set", "u1", {"a": "old", "b": 1}, t=1),
        ]
        pm = aggregate_properties(events)["u1"]
        assert pm.to_dict() == {"a": "new", "b": 1}

    def test_unset(self):
        events = [
            ev("$set", "u1", {"a": 1, "b": 2}, t=0),
            ev("$unset", "u1", {"a": None}, t=1),
        ]
        pm = aggregate_properties(events)["u1"]
        assert pm.to_dict() == {"b": 2}

    def test_unset_then_set_again(self):
        events = [
            ev("$set", "u1", {"a": 1}, t=0),
            ev("$unset", "u1", {"a": None}, t=1),
            ev("$set", "u1", {"a": 9}, t=2),
        ]
        pm = aggregate_properties(events)["u1"]
        assert pm.to_dict() == {"a": 9}

    def test_delete_entity(self):
        events = [
            ev("$set", "u1", {"a": 1}, t=0),
            ev("$delete", "u1", t=1),
        ]
        assert "u1" not in aggregate_properties(events)

    def test_delete_then_set(self):
        events = [
            ev("$set", "u1", {"a": 1}, t=0),
            ev("$delete", "u1", t=1),
            ev("$set", "u1", {"b": 2}, t=2),
        ]
        pm = aggregate_properties(events)["u1"]
        assert pm.to_dict() == {"b": 2}

    def test_multiple_entities(self):
        events = [
            ev("$set", "u1", {"a": 1}, t=0),
            ev("$set", "u2", {"a": 2}, t=0),
        ]
        result = aggregate_properties(events)
        assert result["u1"].to_dict() == {"a": 1}
        assert result["u2"].to_dict() == {"a": 2}

    def test_non_special_ignored(self):
        events = [ev("view", "u1", t=0), ev("$set", "u1", {"a": 1}, t=1)]
        assert aggregate_properties(events)["u1"].to_dict() == {"a": 1}

    def test_of_entity(self):
        events = [
            ev("$set", "u1", {"a": 1}, t=0),
            ev("$set", "u1", {"b": 2}, t=3),
        ]
        pm = aggregate_properties_of_entity(events)
        assert pm is not None
        assert pm.to_dict() == {"a": 1, "b": 2}
        assert pm.last_updated == T(3)

    def test_of_entity_empty(self):
        assert aggregate_properties_of_entity([]) is None

    def test_merge_associativity(self):
        ops = [
            EventOp.from_event(ev("$set", "u", {"a": 1, "b": 1}, t=0)),
            EventOp.from_event(ev("$unset", "u", {"a": None}, t=1)),
            EventOp.from_event(ev("$set", "u", {"a": 7}, t=2)),
            EventOp.from_event(ev("$delete", "u", t=3)),
            EventOp.from_event(ev("$set", "u", {"z": 9}, t=4)),
        ]
        left = ops[0]
        for o in ops[1:]:
            left = left.merge(o)
        right = ops[-1]
        for o in reversed(ops[:-1]):
            right = o.merge(right)
        assert left.to_property_map() == right.to_property_map()
        assert left.to_property_map().to_dict() == {"z": 9}
