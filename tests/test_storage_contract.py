"""Backend-agnostic storage contract suite: every event-store and metadata
backend must pass the same behaviors (pattern from reference
LEventsSpec.scala:21 'behave like any LEvents implementation')."""

import datetime as dt

import pytest

from predictionio_tpu.data import DataMap, Event
from predictionio_tpu.data.storage.base import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EventQuery,
    Model,
)
from predictionio_tpu.data.storage.memory import (
    MemoryAccessKeys,
    MemoryApps,
    MemoryChannels,
    MemoryEngineInstances,
    MemoryEventStore,
    MemoryModels,
)
from predictionio_tpu.data.storage.sqlite import (
    SqliteAccessKeys,
    SqliteApps,
    SqliteChannels,
    SqliteEngineInstances,
    SqliteEventStore,
    SqliteModels,
)
from predictionio_tpu.data.storage.localfs import LocalFSModels

UTC = dt.timezone.utc
APP = 1


def T(i):
    return dt.datetime(2024, 1, 1, tzinfo=UTC) + dt.timedelta(hours=i)


def ev(name, eid, t=0, etype="user", **kw):
    return Event(
        event=name, entity_type=etype, entity_id=eid, event_time=T(t), **kw
    )


def _remote_server(tmp_path):
    """In-process storage daemon backed by throwaway sqlite+localfs."""
    from predictionio_tpu.data.api.storage_server import StorageServer
    from predictionio_tpu.data.storage.registry import (
        SourceConfig,
        Storage,
        StorageConfig,
    )

    cfg = StorageConfig(
        sources={
            "SQL": SourceConfig(
                "SQL", "sqlite", {"PATH": str(tmp_path / "served.db")}
            ),
            "FS": SourceConfig("FS", "localfs", {"PATH": str(tmp_path)}),
        },
        repositories={
            "METADATA": "SQL", "EVENTDATA": "SQL", "MODELDATA": "FS",
        },
    )
    return StorageServer(Storage(cfg), host="127.0.0.1", port=0).start()


def _pg_fake_client():
    """postgres backend over the sqlite-backed fake driver (fake_pg.py) —
    full-contract coverage of the SQL/codec layer without a server."""
    import fake_pg
    from predictionio_tpu.data.storage.postgres import _PGClient

    return _PGClient(conn=fake_pg.connect())


@pytest.fixture(
    params=["memory", "sqlite", "parquetfs", "remote", "postgres", "segmentfs"]
)
def events(request, tmp_path):
    server = None
    if request.param == "memory":
        store = MemoryEventStore()
    elif request.param == "segmentfs":
        from predictionio_tpu.data.storage.segmentfs import (
            SegmentFSEventStore,
        )

        # long sealer interval: the contract must hold on the UNSEALED
        # tail; seal/compact coverage lives in test_segmentfs.py
        store = SegmentFSEventStore(
            {"PATH": str(tmp_path / "seg"), "SEAL_INTERVAL_S": "3600"}
        )
    elif request.param == "postgres":
        from predictionio_tpu.data.storage.postgres import PostgresEventStore

        store = PostgresEventStore(client=_pg_fake_client())
    elif request.param == "parquetfs":
        from predictionio_tpu.data.storage.parquetfs import ParquetFSEventStore

        store = ParquetFSEventStore({"PATH": str(tmp_path / "pq")})
    elif request.param == "remote":
        from predictionio_tpu.data.storage.remote import RemoteEventStore

        server = _remote_server(tmp_path)
        store = RemoteEventStore(
            {"HOST": "127.0.0.1", "PORT": str(server.port)}
        )
    else:
        store = SqliteEventStore({"PATH": str(tmp_path / "ev.db")})
    store.init_app(APP)
    yield store
    store.remove_app(APP)
    store.close()
    if server is not None:
        server.shutdown()


class TestEventStoreContract:
    def test_insert_get_delete(self, events):
        e = ev("view", "u1", t=0)
        eid = events.insert(e, APP)
        got = events.get(eid, APP)
        assert got is not None and got.event == "view" and got.event_id == eid
        assert events.delete(eid, APP)
        assert events.get(eid, APP) is None
        assert not events.delete(eid, APP)

    def test_insert_batch(self, events):
        ids = events.insert_batch([ev("view", f"u{i}", t=i) for i in range(5)], APP)
        assert len(set(ids)) == 5
        found = list(events.find(EventQuery(app_id=APP)))
        assert len(found) == 5

    def test_time_order_and_reversed(self, events):
        events.insert_batch([ev("view", "u", t=i) for i in (3, 1, 2)], APP)
        asc = [e.event_time for e in events.find(EventQuery(app_id=APP))]
        assert asc == sorted(asc)
        desc = [e.event_time for e in events.find(EventQuery(app_id=APP, reversed=True))]
        assert desc == sorted(desc, reverse=True)

    def test_time_range_filter(self, events):
        events.insert_batch([ev("view", "u", t=i) for i in range(5)], APP)
        found = list(
            events.find(EventQuery(app_id=APP, start_time=T(1), until_time=T(3)))
        )
        assert [e.event_time for e in found] == [T(1), T(2)]

    def test_entity_and_event_filters(self, events):
        events.insert(ev("view", "u1"), APP)
        events.insert(ev("buy", "u1", t=1), APP)
        events.insert(ev("view", "u2", t=2), APP)
        events.insert(ev("view", "i1", t=3, etype="item"), APP)
        assert len(list(events.find(EventQuery(app_id=APP, entity_type="user")))) == 3
        assert (
            len(list(events.find(EventQuery(app_id=APP, entity_type="user", entity_id="u1"))))
            == 2
        )
        assert len(list(events.find(EventQuery(app_id=APP, event_names=["buy"])))) == 1

    def test_target_entity_filter(self, events):
        events.insert(
            ev("view", "u1", target_entity_type="item", target_entity_id="i1"), APP
        )
        events.insert(ev("signup", "u1", t=1), APP)
        hit = list(
            events.find(
                EventQuery(app_id=APP, target_entity_type="item", target_entity_id="i1")
            )
        )
        assert len(hit) == 1 and hit[0].event == "view"
        absent = list(events.find(EventQuery(app_id=APP, filter_target_absent=True)))
        assert len(absent) == 1 and absent[0].event == "signup"

    def test_limit(self, events):
        events.insert_batch([ev("view", "u", t=i) for i in range(10)], APP)
        assert len(list(events.find(EventQuery(app_id=APP, limit=3)))) == 3

    def test_channel_isolation(self, events):
        events.init_app(APP, 7)
        events.insert(ev("view", "u1"), APP)
        events.insert(ev("view", "u2"), APP, 7)
        assert len(list(events.find(EventQuery(app_id=APP)))) == 1
        assert len(list(events.find(EventQuery(app_id=APP, channel_id=7)))) == 1
        assert (
            list(events.find(EventQuery(app_id=APP, channel_id=7)))[0].entity_id == "u2"
        )

    def test_properties_roundtrip(self, events):
        e = ev("view", "u1", properties=DataMap({"x": [1, "a"], "y": {"n": 2.5}}))
        eid = events.insert(e, APP)
        got = events.get(eid, APP)
        assert got.properties.to_dict() == {"x": [1, "a"], "y": {"n": 2.5}}

    def test_aggregate_properties(self, events):
        events.insert(
            ev("$set", "u1", t=0, properties=DataMap({"a": 1})), APP
        )
        events.insert(
            ev("$set", "u1", t=1, properties=DataMap({"b": 2})), APP
        )
        events.insert(
            ev("$set", "u2", t=0, properties=DataMap({"a": 5})), APP
        )
        agg = events.aggregate_properties(APP, "user")
        assert agg["u1"].to_dict() == {"a": 1, "b": 2}
        assert agg["u2"].to_dict() == {"a": 5}
        # required-field filter
        agg2 = events.aggregate_properties(APP, "user", required=["b"])
        assert set(agg2) == {"u1"}

    def test_find_single_entity_newest_first(self, events):
        events.insert_batch([ev("view", "u1", t=i) for i in range(3)], APP)
        got = list(events.find_single_entity(APP, "user", "u1", limit=2))
        assert len(got) == 2
        assert got[0].event_time > got[1].event_time

    def test_find_entities_batch(self, events):
        """Batched serving read: every listed entity answered in one
        call, newest-first, per-entity-limited, event-name-filtered."""
        batch = []
        for u in ("u1", "u2"):
            batch.extend(ev("view", u, t=i) for i in range(3))
            batch.append(ev("buy", u, t=9))
        events.insert_batch(batch, APP)
        out = events.find_entities_batch(
            APP, "user", ["u1", "u2", "ghost"],
            event_names=["view"], limit_per_entity=2,
        )
        assert set(out) == {"u1", "u2", "ghost"}
        assert out["ghost"] == []
        for u in ("u1", "u2"):
            got = out[u]
            assert len(got) == 2
            assert all(e.event == "view" and e.entity_id == u for e in got)
            assert got[0].event_time > got[1].event_time


@pytest.fixture(params=["memory", "sqlite", "remote", "postgres", "docfs"])
def meta(request, tmp_path):
    if request.param == "docfs":
        from predictionio_tpu.data.storage.docfs import (
            DocFSAccessKeys,
            DocFSApps,
            DocFSChannels,
            DocFSEngineInstances,
            DocFSModels,
            _DocFSClient,
        )

        client = _DocFSClient({"PATH": str(tmp_path / "docfs")})
        yield {
            "apps": DocFSApps(client=client),
            "keys": DocFSAccessKeys(client=client),
            "channels": DocFSChannels(client=client),
            "instances": DocFSEngineInstances(client=client),
            "models": DocFSModels(client=client),
        }
        return
    if request.param == "postgres":
        from predictionio_tpu.data.storage.postgres import (
            PostgresAccessKeys,
            PostgresApps,
            PostgresChannels,
            PostgresEngineInstances,
            PostgresModels,
        )

        client = _pg_fake_client()
        yield {
            "apps": PostgresApps({}, client=client),
            "keys": PostgresAccessKeys({}, client=client),
            "channels": PostgresChannels({}, client=client),
            "instances": PostgresEngineInstances({}, client=client),
            "models": PostgresModels({}, client=client),
        }
        return
    if request.param == "memory":
        yield {
            "apps": MemoryApps(),
            "keys": MemoryAccessKeys(),
            "channels": MemoryChannels(),
            "instances": MemoryEngineInstances(),
            "models": MemoryModels(),
        }
        return
    if request.param == "remote":
        from predictionio_tpu.data.storage.remote import (
            RemoteAccessKeys,
            RemoteApps,
            RemoteChannels,
            RemoteClient,
            RemoteEngineInstances,
            RemoteModels,
        )

        server = _remote_server(tmp_path)
        client = RemoteClient(
            {"HOST": "127.0.0.1", "PORT": str(server.port)}
        )
        yield {
            "apps": RemoteApps({}, client=client),
            "keys": RemoteAccessKeys({}, client=client),
            "channels": RemoteChannels({}, client=client),
            "instances": RemoteEngineInstances({}, client=client),
            "models": RemoteModels({}, client=client),
        }
        server.shutdown()
        return
    cfg = {"PATH": str(tmp_path / "meta.db")}
    yield {
        "apps": SqliteApps(cfg),
        "keys": SqliteAccessKeys(cfg),
        "channels": SqliteChannels(cfg),
        "instances": SqliteEngineInstances(cfg),
        "models": SqliteModels(cfg),
    }


class TestMetadataContract:
    def test_apps_crud(self, meta):
        apps = meta["apps"]
        aid = apps.insert(App(0, "myapp", "desc"))
        assert aid and aid > 0
        assert apps.get(aid).name == "myapp"
        assert apps.get_by_name("myapp").id == aid
        assert apps.insert(App(0, "myapp")) is None  # duplicate name
        assert apps.update(App(aid, "renamed", None))
        assert apps.get_by_name("renamed") is not None
        assert apps.delete(aid)
        assert apps.get(aid) is None

    def test_access_keys(self, meta):
        keys = meta["keys"]
        k = keys.insert(AccessKey("", 1, ("view", "buy")))
        assert k and len(k) > 10
        got = keys.get(k)
        assert got.app_id == 1 and got.events == ("view", "buy")
        k2 = keys.insert(AccessKey("fixedkey", 2))
        assert k2 == "fixedkey"
        assert {x.key for x in keys.get_by_app_id(1)} == {k}
        assert keys.delete(k)
        assert keys.get(k) is None

    def test_channels(self, meta):
        channels = meta["channels"]
        cid = channels.insert(Channel(0, "ch-1", 1))
        assert cid and channels.get(cid).name == "ch-1"
        assert channels.insert(Channel(0, "bad name!", 1)) is None
        assert channels.insert(Channel(0, "ch-1", 1)) is None  # dup per app
        assert channels.insert(Channel(0, "ch-1", 2)) is not None  # other app ok
        assert [c.id for c in channels.get_by_app_id(1)] == [cid]
        assert channels.delete(cid)

    def test_engine_instances_lifecycle(self, meta):
        instances = meta["instances"]
        base_kwargs = dict(
            engine_id="eng", engine_version="1", engine_variant="default.json",
            engine_factory="f",
        )
        i1 = instances.insert(
            EngineInstance(id="", status="INIT", start_time=T(0), end_time=T(0), **base_kwargs)
        )
        rec = instances.get(i1)
        assert rec.status == "INIT"
        rec.status = "COMPLETED"
        assert instances.update(rec)
        i2 = instances.insert(
            EngineInstance(id="", status="COMPLETED", start_time=T(5), end_time=T(5), **base_kwargs)
        )
        latest = instances.get_latest_completed("eng", "1", "default.json")
        assert latest.id == i2
        assert len(instances.get_completed("eng", "1", "default.json")) == 2
        assert instances.get_latest_completed("other", "1", "x") is None

    def test_models_blob(self, meta):
        models = meta["models"]
        blob = b"\x00\x01binary\xff" * 100
        models.insert(Model("m1", blob))
        assert models.get("m1").models == blob
        models.insert(Model("m1", b"v2"))  # overwrite
        assert models.get("m1").models == b"v2"
        models.delete("m1")
        assert models.get("m1") is None


class TestLocalFSModels:
    def test_blob_roundtrip(self, tmp_path):
        store = LocalFSModels({"PATH": str(tmp_path)})
        store.insert(Model("abc123", b"\x00blob\xff"))
        assert store.get("abc123").models == b"\x00blob\xff"
        assert store.get("missing") is None
        store.delete("abc123")
        assert store.get("abc123") is None


class TestRegistry:
    def test_env_parse(self):
        env = {
            "PIO_STORAGE_SOURCES_MYSQL_TYPE": "sqlite",
            "PIO_STORAGE_SOURCES_MYSQL_PATH": "/tmp/x.db",
            "PIO_STORAGE_SOURCES_FS_TYPE": "localfs",
            "PIO_STORAGE_SOURCES_FS_PATH": "/tmp/fs",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MYSQL",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MYSQL",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "FS",
        }
        from predictionio_tpu.data.storage.registry import StorageConfig

        cfg = StorageConfig.from_env(env)
        assert cfg.sources["MYSQL"].type == "sqlite"
        assert cfg.sources["MYSQL"].settings["PATH"] == "/tmp/x.db"
        assert cfg.repositories["MODELDATA"] == "FS"

    def test_verify_all(self, fresh_storage):
        results = fresh_storage.verify_all_data_objects()
        assert len(results) >= 8
        assert all(r.startswith("OK") for r in results)

    def test_dao_singletons(self, fresh_storage):
        assert fresh_storage.get_events() is fresh_storage.get_events()
        assert fresh_storage.get_meta_data_apps() is fresh_storage.get_meta_data_apps()


class TestFindFrameContract:
    """Columnar training-read fast path, for backends that provide it
    (sqlite json_extract pushdown, parquetfs column projection, postgres
    host-side pull)."""

    def test_find_frame_values_and_order(self, events):
        if not hasattr(events, "find_frame"):
            pytest.skip("backend uses the base find() fallback")
        evs = [
            ev("rate", f"u{i}", t=i, target_entity_type="item",
               target_entity_id=f"i{i % 3}",
               properties=DataMap({"rating": float(i + 1)}))
            for i in range(6)
        ]
        events.insert_batch(evs, APP)
        frame = events.find_frame(
            EventQuery(app_id=APP), value_prop="rating", default_value=9.0
        )
        assert len(frame) == 6
        assert frame.value.tolist() == [1.0, 2.0, 3.0, 4.0, 5.0, 6.0]
        # and the default applies when the property is absent
        events.insert(
            ev("rate", "u9", t=10, target_entity_type="item",
               target_entity_id="i0"),
            APP,
        )
        frame = events.find_frame(
            EventQuery(app_id=APP), value_prop="rating", default_value=9.0
        )
        assert frame.value.tolist()[-1] == 9.0


class TestDataSignature:
    """data_signature: cheap monotone namespace fingerprint (DataView key)."""

    def test_changes_on_write_and_delete(self, events):
        s0 = events.data_signature(APP)
        eid = events.insert(ev("view", "u1"), APP)
        s1 = events.data_signature(APP)
        assert s1 != s0
        events.insert(ev("view", "u2", t=1), APP)
        s2 = events.data_signature(APP)
        assert s2 != s1
        events.delete(eid, APP)
        s3 = events.data_signature(APP)
        assert s3 != s2


def test_docfs_metadata_with_sql_events_end_to_end(tmp_path):
    """Split-repository topology (the reference's ES config): METADATA on
    the document store, EVENTDATA on SQL — full train → latest-completed
    lookup crosses both backends."""
    import numpy as np

    from predictionio_tpu.data.storage.registry import (
        SourceConfig,
        Storage,
        StorageConfig,
    )
    from predictionio_tpu.workflow.core import run_train
    from predictionio_tpu.workflow.server import latest_completed_runtime

    cfg = StorageConfig(
        sources={
            "DOC": SourceConfig("DOC", "docfs", {"PATH": str(tmp_path / "meta")}),
            "SQL": SourceConfig("SQL", "sqlite", {"PATH": str(tmp_path / "ev.db")}),
        },
        repositories={
            "METADATA": "DOC", "EVENTDATA": "SQL", "MODELDATA": "DOC",
        },
    )
    storage = Storage(cfg)
    app_id = storage.get_meta_data_apps().insert(App(0, "docapp"))
    assert app_id and app_id > 0
    events = storage.get_events()
    events.init_app(app_id)
    rng = np.random.RandomState(0)
    events.insert_batch(
        [
            ev("rate", f"u{rng.randint(6)}", t=i % 48,
               target_entity_type="item",
               target_entity_id=f"i{rng.randint(10)}",
               properties=DataMap({"rating": float(rng.randint(1, 6))}))
            for i in range(120)
        ],
        app_id,
    )
    variant = {
        "id": "docrun",
        "engineFactory":
            "predictionio_tpu.engines.recommendation.RecommendationEngine",
        "datasource": {"params": {"app_name": "docapp"}},
        "algorithms": [
            {"name": "als", "params": {"rank": 4, "num_iterations": 2}}
        ],
    }
    inst = run_train(storage, variant)
    assert inst.status == "COMPLETED"
    runtime = latest_completed_runtime(storage, "docrun", "0", "docrun")
    assert runtime.instance.id == inst.id
    # manifest registered in the document store too
    m = storage.get_meta_data_engine_manifests().get("docrun", "0")
    assert m is not None and m.engine_factory == variant["engineFactory"]


def test_docfs_id_allocation_skips_explicit_ids(tmp_path):
    """Auto-ids must never collide with (and overwrite) an explicitly
    inserted id (code-review r3): the row document's exclusive create is
    the authoritative allocation."""
    from predictionio_tpu.data.storage.docfs import DocFSApps, _DocFSClient

    apps = DocFSApps(client=_DocFSClient({"PATH": str(tmp_path / "d")}))
    assert apps.insert(App(3, "explicit")) == 3
    ids = [apps.insert(App(0, f"auto{i}")) for i in range(4)]
    assert 3 not in ids and len(set(ids)) == 4
    assert apps.get(3).name == "explicit"  # untouched
    # duplicate names refused even via the reservation path
    assert apps.insert(App(0, "explicit")) is None
    # rename moves the reservation: old name becomes free
    assert apps.update(App(3, "renamed"))
    assert apps.insert(App(0, "explicit")) is not None
