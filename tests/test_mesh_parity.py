"""Sharded-vs-single-device numerical parity for every model kernel.

The multi-chip re-design's correctness contract: partitioned aggregation
(GSPMD psum over the dp axis) must reproduce the single-device fold, the
same invariant the reference's partitioned aggregateByKey relies on
(data/.../storage/PEventAggregator.scala:85-191)."""

import numpy as np
import pytest

from predictionio_tpu.models import classify


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.RandomState(7)
    n, d, c = 203, 7, 4  # n deliberately not divisible by 8
    x = rng.rand(n, d).astype(np.float32)
    # planted structure so accuracy is meaningful
    w_true = rng.randn(d, c).astype(np.float32) * 3.0
    y = (x @ w_true + 0.3 * rng.randn(n, c)).argmax(axis=1).astype(np.int32)
    return x, y, c


def test_naive_bayes_mesh_parity(mesh8, dataset):
    x, y, c = dataset
    m0 = classify.train_naive_bayes(x, y, c)
    m1 = classify.train_naive_bayes(x, y, c, mesh=mesh8)
    np.testing.assert_allclose(m0.log_prior, m1.log_prior, atol=1e-5)
    np.testing.assert_allclose(
        m0.log_likelihood, m1.log_likelihood, atol=1e-5
    )


def test_logistic_regression_mesh_parity(mesh8, dataset):
    x, y, c = dataset
    m0 = classify.train_logistic_regression(x, y, c, iterations=200)
    m1 = classify.train_logistic_regression(
        x, y, c, iterations=200, mesh=mesh8
    )
    np.testing.assert_allclose(m0.weights, m1.weights, atol=1e-4)
    assert (m0.predict(x) == m1.predict(x)).all()
    assert (m0.predict(x) == y).mean() > 0.8  # planted structure recovered


def test_cco_mesh_parity(mesh8):
    from predictionio_tpu.models import cco

    rng = np.random.RandomState(3)
    n_u, n_i, n_j = 41, 16, 12  # user dim not divisible by 8
    primary = (rng.rand(n_u, n_i) < 0.25).astype(np.float32)
    secondary = (rng.rand(n_u, n_j) < 0.25).astype(np.float32)
    s0, i0 = cco.cross_occurrence_topn(primary, secondary, top_n=5)
    s1, i1 = cco.cross_occurrence_topn(
        primary, secondary, top_n=5, mesh=mesh8
    )
    np.testing.assert_allclose(s0, s1, atol=1e-4)
    assert (i0 == i1).all()
