"""Durable long-horizon TSDB (ISSUE 18): the columnar block format,
WAL flush/seal/replay, restart-boundary counter math (increase/rate
across a kill -9 with no phantom reset), downsampling compaction with
the documented edge-bucket bound, per-tier retention that never
outruns the next tier's watermark, tier selection for long windows,
multi-window burn-rate SLO specs, and the Monitor/console wiring."""

import os
import signal
import subprocess
import sys
import time

import pytest

from predictionio_tpu.obs.monitor.compact import (
    DEFAULT_RETENTION,
    Compactor,
)
from predictionio_tpu.obs.monitor.durable import (
    BlockHandle,
    DurableTSDB,
    TIER_BUCKETS,
    write_block,
)
from predictionio_tpu.obs.monitor.slo import SLOEngine, SLOSpec
from predictionio_tpu.obs.monitor.tsdb import TSDB

T0 = 1_700_000_000.0

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_DIR = os.path.dirname(TESTS_DIR)


def _mk(tmp_path, **kw):
    """A DurableTSDB with background flushing effectively disabled —
    tests drive flush_once()/seal explicitly."""
    kw.setdefault("capacity", 720)
    kw.setdefault("flush_interval_s", 9999.0)
    kw.setdefault("seal_age_s", 9999.0)
    return DurableTSDB(str(tmp_path / "tsdb"), **kw)


def _walk(db, name, labels, start, end, step, rate, kind="counter",
          v0=0.0):
    """Write a counter climbing `rate` per point every `step` s;
    returns the final value."""
    v = v0
    t = start
    while t <= end:
        v += rate
        db.add(name, labels, v, kind, t)
        t += step
    return v


# ---------------------------------------------------------------------------
# block format
# ---------------------------------------------------------------------------


class TestBlockFormat:
    def test_roundtrip(self, tmp_path):
        path = str(tmp_path / "b-1-2-w00000001.blk")
        ts = [int((T0 + i * 10) * 1000) for i in range(50)]
        vals = [float(i) * 1.5 for i in range(50)]
        rows = [("m", (("a", "1"),), "counter", ts, {"v": vals})]
        footer = write_block(path, "raw", rows)
        assert footer is not None
        h = BlockHandle.load(path)
        got_ts, cols = h.read_series(("m", (("a", "1"),)))
        assert got_ts == pytest.approx([t / 1000.0 for t in ts])
        assert cols["v"] == pytest.approx(vals)
        assert h.read_series(("missing", ())) is None

    def test_corrupt_block_is_skipped_not_fatal(self, tmp_path):
        db = _mk(tmp_path)
        _walk(db, "c", {}, T0, T0 + 600, 10.0, 1.0)
        db.flush_once(seal=True)
        blocks = db.tiers["raw"].blocks()
        assert blocks
        # truncate one block mid-body: the index must drop it and
        # queries must keep answering from memory
        with open(blocks[0].path, "r+b") as f:
            f.truncate(10)
        db.tiers["raw"].invalidate()
        assert db.tiers["raw"].blocks() == []
        assert db.summary()["durable"]["tiers"]["raw"]["blocks"] == 0
        db.stop()


# ---------------------------------------------------------------------------
# WAL + replay: the restart boundary
# ---------------------------------------------------------------------------


class TestRestartBoundary:
    def test_replay_restores_history(self, tmp_path):
        db = _mk(tmp_path)
        _walk(db, "reqs", {"code": "200"}, T0, T0 + 3600, 10.0, 4.0)
        db.flush_once(seal=True)
        db.stop()
        db2 = _mk(tmp_path)
        assert db2.replayed_points > 0
        s = db2.matching("reqs", {"code": "200"})
        assert len(s) == 1
        now = T0 + 3600
        pts = db2.points(s[0], 3600.0, now)
        assert len(pts) >= 300
        db2.stop()

    def test_increase_across_restart_matches_no_restart(self, tmp_path):
        """The acceptance criterion: increase()/rate() over a window
        straddling the restart equal the uninterrupted values — no
        phantom reset at the boundary."""
        now = T0 + 7200
        ref = TSDB(capacity=4096)
        db = _mk(tmp_path)
        for target in (ref, db):
            _walk(target, "reqs", {}, T0, T0 + 3600, 10.0, 4.0)
        db.flush_once(seal=True)
        db.stop()
        db2 = _mk(tmp_path)
        # post-restart traffic continues the SAME counter (a monitor
        # restart, not a process restart of the counted service)
        for target in (ref, db2):
            _walk(target, "reqs", {}, T0 + 3610, now, 10.0, 4.0,
                  v0=4.0 * 361)
        rs = ref.matching("reqs", None)[0]
        ds = db2.matching("reqs", None)[0]
        for window in (1800.0, 3600.0, 7200.0):
            want = ref.series_increase(rs, window, now)
            got = db2.series_increase(ds, window, now)
            assert got == pytest.approx(want, abs=1e-6), (
                f"window={window}: {got} != {want}"
            )
        db2.stop()

    def test_genuine_reset_inside_window_still_detected(self, tmp_path):
        """A real counter restart (value drops to ~0) inside a window
        that also straddles the monitor restart must still count the
        post-reset accumulation — reset-awareness survives tiering."""
        now = T0 + 7200
        db = _mk(tmp_path)
        _walk(db, "c", {}, T0, T0 + 3600, 10.0, 1.0)  # → 361
        db.flush_once(seal=True)
        db.stop()
        db2 = _mk(tmp_path)
        # the counted process restarts: counter starts over from 0
        _walk(db2, "c", {}, T0 + 3610, now, 10.0, 1.0, v0=0.0)
        s = db2.matching("c", None)[0]
        got = db2.series_increase(s, 7200.0, now)
        # 361 pre-restart + 360 post-reset accumulation
        assert got == pytest.approx(361 + 360, abs=1.0)
        db2.stop()

    def test_unsealed_wal_tail_replays(self, tmp_path):
        """Points flushed to the WAL but never sealed into a block
        (the kill -9 shape) still come back."""
        db = _mk(tmp_path)
        _walk(db, "g", {}, T0, T0 + 100, 10.0, 1.0, kind="gauge")
        db.flush_once(seal=False)  # WAL only, no block
        # no stop(): simulate an abrupt death
        assert db.tiers["raw"].blocks() == []
        db2 = _mk(tmp_path)
        s = db2.matching("g", None)
        assert s and len(db2.points(s[0], 3600.0, T0 + 100)) == 11
        db2.stop()
        db._stop.set()  # silence the leak tripwire for the orphan

    def test_kill9_subprocess_history_survives(self, tmp_path):
        """End-to-end: a separate process seeds the durable dir, dies
        by SIGKILL mid-flight, and a fresh process (the `pio tsdb
        query` shape) reads the pre-kill history."""
        d = str(tmp_path / "tsdb")
        script = f"""
import os, signal
from predictionio_tpu.obs.monitor.durable import DurableTSDB
db = DurableTSDB({d!r}, flush_interval_s=9999, seal_age_s=9999)
v = 0.0
for i in range(361):
    v += 4.0
    db.add("reqs", {{}}, v, "counter", {T0} + i * 10.0)
db.flush_once()
os.kill(os.getpid(), signal.SIGKILL)
"""
        proc = subprocess.run(
            [sys.executable, "-c", script], cwd=REPO_DIR,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            timeout=120,
        )
        assert proc.returncode == -signal.SIGKILL
        db = _mk(tmp_path)
        s = db.matching("reqs", None)
        assert s, "pre-kill history lost"
        inc = db.series_increase(s[0], 3600.0, T0 + 3600)
        assert inc == pytest.approx(4.0 * 360, abs=4.0)
        db.stop()


# ---------------------------------------------------------------------------
# downsampling compaction
# ---------------------------------------------------------------------------


class TestCompaction:
    def _seeded(self, tmp_path, hours=8.0, step=30.0, rate=3.0):
        db = _mk(tmp_path, capacity=120)
        now = T0 + hours * 3600
        _walk(db, "reqs", {}, T0, now, step, rate)
        db.flush_once(seal=True)
        return db, now

    def test_downsample_produces_tiers(self, tmp_path):
        db, now = self._seeded(tmp_path)
        comp = Compactor(db, interval_s=9999.0)
        res = comp.run_once(now=now, force=True)
        assert res["buckets"] > 0
        assert db.tiers["5m"].stats()["blocks"] >= 1
        assert db.tiers["1h"].stats()["blocks"] >= 1
        assert comp.stats()["compacted_blocks"] >= 2
        db.stop()

    def test_downsampled_increase_within_edge_bound(self, tmp_path):
        """Documented bound: an increase answered from a downsampled
        tier may miss/add at most one bucket's worth of counter travel
        per window edge."""
        db, now = self._seeded(tmp_path, step=30.0, rate=3.0)
        Compactor(db, interval_s=9999.0).run_once(now=now, force=True)
        s = db.matching("reqs", None)[0]
        per_s = 3.0 / 30.0
        for window in (6 * 3600.0, 8 * 3600.0):
            tier = db._pick_tier(window, now - window)
            bucket = TIER_BUCKETS[tier]
            want = per_s * min(window, 8 * 3600.0)
            got = db.series_increase(s, window, now)
            # documented bound: one partial bucket of slop per edge
            assert abs(got - want) <= 2 * bucket * per_s + 3.0, (
                f"window={window} tier={tier}: {got} vs {want}"
            )
        db.stop()

    def test_raw_and_downsampled_agree(self, tmp_path):
        """Before retention prunes raw, the same window answered from
        raw points and from 5m buckets agree within the bound."""
        db, now = self._seeded(tmp_path, hours=3.0)
        Compactor(db, interval_s=9999.0).run_once(now=now, force=True)
        key = ("reqs", ())
        window = 2 * 3600.0
        cutoff, edge = now - window, now
        raw_inc, _ = db._disk_increase(key, cutoff, edge, window,
                                       tier="raw")
        ds_inc, _ = db._disk_increase(key, cutoff, edge, window,
                                      tier="5m")
        assert ds_inc == pytest.approx(raw_inc, abs=2 * 300.0 * 0.1 + 1)
        db.stop()

    def test_retention_waits_for_downsampling(self, tmp_path):
        """Raw blocks older than retention survive until the 5m tier's
        watermark passes them — pruning never eats unrolled data."""
        db, now = self._seeded(tmp_path, hours=2.0)
        raw_before = db.tiers["raw"].stats()["blocks"]
        assert raw_before > 0
        comp = Compactor(db, interval_s=9999.0,
                         retention={"raw": 0.001})
        # force=False + huge grace: nothing downsampled yet, so nothing
        # may be pruned either
        comp.grace_s = 1e9
        comp.run_once(now=now)
        assert db.tiers["raw"].stats()["blocks"] == raw_before
        # now roll up, then retention may prune rolled raw blocks
        # (a beat later, so the newest point has aged past retention)
        comp.grace_s = 0.0
        comp.run_once(now=now, force=True)
        comp.run_once(now=now + 60.0)
        assert db.tiers["raw"].stats()["blocks"] < raw_before
        db.stop()

    def test_default_retention_ordering(self):
        assert DEFAULT_RETENTION["raw"] < DEFAULT_RETENTION["5m"]
        assert DEFAULT_RETENTION["5m"] < DEFAULT_RETENTION["1h"]

    def test_compactor_thread_lifecycle(self, tmp_path):
        import threading

        db = _mk(tmp_path)
        comp = Compactor(db, interval_s=9999.0)
        comp.start()
        assert any(
            t.name == "tsdb-compactor" for t in threading.enumerate()
        )
        comp.stop()
        assert not any(
            t.name == "tsdb-compactor" for t in threading.enumerate()
        )
        db.stop()


# ---------------------------------------------------------------------------
# tier selection + long-window queries
# ---------------------------------------------------------------------------


class TestTierSelection:
    def test_long_window_picks_coarse_tier(self, tmp_path):
        db = _mk(tmp_path, capacity=60)
        now = T0 + 3 * 86400
        _walk(db, "reqs", {}, T0, now, 300.0, 30.0)
        db.flush_once(seal=True)
        Compactor(db, interval_s=9999.0).run_once(now=now, force=True)
        assert db._pick_tier(3 * 86400.0, now - 3 * 86400.0) == "1h"
        assert db._pick_tier(2 * 3600.0, now - 2 * 3600.0) in ("raw",
                                                               "5m")
        s = db.matching("reqs", None)[0]
        want = (30.0 / 300.0) * 3 * 86400
        got = db.series_increase(s, 3 * 86400.0, now)
        assert got == pytest.approx(want, rel=0.02)
        db.stop()

    def test_three_day_query_latency(self, tmp_path):
        """BENCH acceptance shape: p50 of a 3-day increase query must
        be far under 100ms once tiered."""
        db = _mk(tmp_path, capacity=60)
        now = T0 + 3 * 86400
        _walk(db, "reqs", {}, T0, now, 300.0, 30.0)
        db.flush_once(seal=True)
        Compactor(db, interval_s=9999.0).run_once(now=now, force=True)
        s = db.matching("reqs", None)[0]
        times = []
        for _ in range(20):
            t0 = time.perf_counter()
            db.series_increase(s, 3 * 86400.0, now)
            times.append(time.perf_counter() - t0)
        times.sort()
        assert times[len(times) // 2] < 0.1
        db.stop()


# ---------------------------------------------------------------------------
# multi-window burn-rate SLOs
# ---------------------------------------------------------------------------


def _burn_spec(**kw):
    kw.setdefault("name", "api")
    kw.setdefault("kind", "expr")
    kw.setdefault(
        "expr",
        "sum(increase(errs[$window])) / sum(increase(reqs[$window]))",
    )
    kw.setdefault("objective", 0.99)
    kw.setdefault("window_s", 3600.0)
    kw.setdefault("fast_window_s", 300.0)
    kw.setdefault("burn_threshold", 2.0)
    return SLOSpec(**kw)


class TestMultiWindowSLO:
    def test_extra_pairs_normalize_and_roundtrip(self):
        spec = _burn_spec(extra_pairs=(
            {"fast_window_s": 21600.0, "window_s": 259200.0,
             "burn_threshold": 1.0},
            (1800, 21600, 1.5),
        ))
        assert spec.burn_pairs == (
            (300.0, 3600.0, 2.0),
            (21600.0, 259200.0, 1.0),
            (1800.0, 21600.0, 1.5),
        )
        again = SLOSpec.from_dict(spec.to_dict())
        assert again.burn_pairs == spec.burn_pairs

    def test_extra_pairs_validation(self):
        with pytest.raises(ValueError):
            _burn_spec(extra_pairs=((3600.0, 300.0, 1.0),))  # fast>slow
        with pytest.raises(ValueError):
            _burn_spec(extra_pairs=((0.0, 300.0, 1.0),))
        with pytest.raises(ValueError):
            _burn_spec(extra_pairs=({"nope": 1},))

    def test_six_hour_pair_fires_from_replayed_burn(self, tmp_path):
        """The acceptance criterion: after a restart the fast 5m/1h
        pair is empty, but the 6h/3d ladder pair reads the replayed
        disk tier and fires."""
        spec = _burn_spec(extra_pairs=(
            {"fast_window_s": 21600.0, "window_s": 259200.0,
             "burn_threshold": 1.0},
        ))
        now = T0 + 3 * 86400
        db = _mk(tmp_path)
        total = err = 0.0
        t = now - 3 * 86400
        while t < now - 2 * 3600:  # silence for the last 2h
            total += 100.0
            err += 5.0  # 5% errors = 5x burn of a 1% budget
            db.add("reqs", {}, total, "counter", t)
            db.add("errs", {}, err, "counter", t)
            t += 600.0
        db.flush_once(seal=True)
        db.stop()
        db2 = _mk(tmp_path)
        eng = SLOEngine(db2, specs=[spec], interval_s=9999.0)
        eng.evaluate_once(now=now)
        st = eng.status("api").to_dict()
        assert st["state"] in ("pending", "firing")
        pairs = st["pairs"]
        assert pairs[0]["fast_burn"] is None  # fast pair: no traffic
        assert pairs[1]["fast_burn"] == pytest.approx(5.0, rel=0.15)
        assert pairs[1]["slow_burn"] == pytest.approx(5.0, rel=0.15)
        db2.stop()

    def test_all_pairs_quiet_holds_state(self, tmp_path):
        spec = _burn_spec(extra_pairs=((1800.0, 7200.0, 1.0),))
        db = TSDB(capacity=64)
        eng = SLOEngine(db, specs=[spec], interval_s=9999.0)
        eng.evaluate_once(now=T0)
        st = eng.status("api").to_dict()
        assert st["state"] == "inactive"
        assert all(p["fast_burn"] is None for p in st["pairs"])


# ---------------------------------------------------------------------------
# Monitor + console wiring
# ---------------------------------------------------------------------------


class TestMonitorWiring:
    def test_pio_tsdb_dir_builds_durable(self, tmp_path, monkeypatch):
        import threading

        from predictionio_tpu.obs.monitor import Monitor
        from predictionio_tpu.obs.registry import MetricsRegistry

        monkeypatch.setenv("PIO_TSDB_DIR", str(tmp_path / "t"))
        monkeypatch.setenv("PIO_TSDB_FLUSH_S", "9999")
        monkeypatch.setenv("PIO_TSDB_COMPACT_S", "9999")
        monitor = Monitor()
        assert isinstance(monitor.tsdb, DurableTSDB)
        assert monitor.snapshot_path is None  # durable supersedes it
        token = monitor.attach("t", MetricsRegistry())
        names = {t.name for t in threading.enumerate()}
        assert "tsdb-wal" in names and "tsdb-compactor" in names
        payload = monitor.tsdb_payload({})
        assert "durable" in payload
        assert payload["durable"]["dir"] == str(tmp_path / "t")
        monitor.detach(token)
        names = {t.name for t in threading.enumerate()}
        assert "tsdb-wal" not in names
        assert "tsdb-compactor" not in names

    def test_console_summary_prints_durable(self, tmp_path, monkeypatch,
                                            capsys):
        from predictionio_tpu.obs import monitor as monitor_pkg
        from predictionio_tpu.obs.monitor import Monitor
        from predictionio_tpu.tools.console import cmd_tsdb

        monkeypatch.setenv("PIO_TSDB_DIR", str(tmp_path / "t"))
        monkeypatch.setenv("PIO_TSDB_FLUSH_S", "9999")
        m = Monitor()
        monkeypatch.setattr(monitor_pkg, "_monitor", m)
        m.tsdb.add("x", {}, 1.0, "gauge", T0)
        m.tsdb.flush_once(seal=True)

        class Args:
            url = None
            expr = None
            name = None
            labels = None
            window = None
            agg = None
            q = None
            last = None

        assert cmd_tsdb(Args()) == 0
        out = capsys.readouterr().out
        assert "durable tier at" in out
        assert "tier raw" in out
        m.tsdb.stop()


# ---------------------------------------------------------------------------
# WAL replay checkpoint cursor (ISSUE 19 satellite): a fat unsealed tail
# must not be re-parsed from byte 0 on every attach
# ---------------------------------------------------------------------------


class TestReplayCheckpoint:
    def test_replay_skips_pre_checkpoint_wal_bytes(self, tmp_path):
        """Fat-tail regression: after a checkpoint, reopening reads the
        active WAL segment from the cursor's byte offset — the thousands
        of pre-checkpoint lines are seeded from the snapshot, not
        re-parsed."""
        db = _mk(tmp_path)
        _walk(db, "fat", {"h": "a"}, T0, T0 + 30_000, 10.0, 1.0)  # 3001 pts
        db.flush_once(seal=False)
        cur = db.checkpoint_once()
        assert cur["off"] > 0
        last = _walk(db, "fat", {"h": "a"}, T0 + 30_010, T0 + 30_500,
                     10.0, 1.0, v0=3001.0)
        db.stop()

        reads = []
        real = DurableTSDB._read_wal_segment

        def spy(path, offset=0):
            reads.append((os.path.basename(path), offset))
            return real(path, offset)

        DurableTSDB._read_wal_segment = staticmethod(spy)
        try:
            db2 = _mk(tmp_path)
        finally:
            DurableTSDB._read_wal_segment = staticmethod(real)
        # every replay read of the checkpointed segment started at the
        # cursor offset — no read from byte 0
        seg = f"w-{cur['seq']:08d}.log"
        seg_reads = [off for name, off in reads if name == seg]
        assert seg_reads and all(off == cur["off"] for off in seg_reads)
        assert db2.ckpt_seeded_points > 0
        # and nothing was lost past the mark: the post-checkpoint walk
        # is all there
        pts = db2.matching("fat", {"h": "a"})[0].points
        assert pts[-1][1] == pytest.approx(last)
        stats = db2.durable_stats()
        assert stats["ckpt_seeded_points"] == db2.ckpt_seeded_points
        db2.stop()

    def test_checkpoint_replay_matches_full_replay(self, tmp_path):
        """Seeding from the snapshot + post-mark bytes must reconstruct
        exactly the rings a full WAL re-read builds."""
        import shutil

        db = _mk(tmp_path)
        for h in ("a", "b"):
            _walk(db, "m", {"h": h}, T0, T0 + 12_000, 10.0, 1.0)
        db.flush_once(seal=False)
        db.checkpoint_once()
        for h in ("a", "b"):
            _walk(db, "m", {"h": h}, T0 + 12_010, T0 + 12_300, 10.0, 1.0,
                  v0=1201.0)
        db.stop()
        shutil.copytree(str(tmp_path / "tsdb"), str(tmp_path / "full"))
        os.remove(str(tmp_path / "full" / "wal" / "ckpt.json"))

        with_ckpt = _mk(tmp_path)
        no_ckpt = DurableTSDB(str(tmp_path / "full"), capacity=720,
                              flush_interval_s=9999.0, seal_age_s=9999.0)
        assert with_ckpt.ckpt_seeded_points > 0
        assert no_ckpt.ckpt_seeded_points == 0
        for h in ("a", "b"):
            assert list(with_ckpt.matching("m", {"h": h})[0].points) == \
                list(no_ckpt.matching("m", {"h": h})[0].points)
        with_ckpt.stop()
        no_ckpt.stop()

    def test_periodic_checkpoint_rides_flush(self, tmp_path):
        db = _mk(tmp_path, ckpt_points=100)
        _walk(db, "c", {}, T0, T0 + 2_500, 10.0, 1.0)  # 251 points
        db.flush_once(seal=False)
        assert db.ckpt_written >= 1
        stats = db.durable_stats()
        assert stats["wal"]["ckpt_pending_points"] < 100
        assert stats["ckpt_written"] == db.ckpt_written
        db.stop()
