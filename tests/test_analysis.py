"""ISSUE 12: the in-tree invariant analyzer (`pio lint`) + thread
sanitizer. Positive/negative fixture snippets per checker, suppression
handling, the env-knob registry, the seeded AB/BA lock inversion, the
thread-leak tripwire, the blocked-while-holding hook, the console
round-trip — and the gate itself: the real package must lint clean."""

from __future__ import annotations

import json
import threading
import time

import pytest

from predictionio_tpu.analysis import lint as lint_mod
from predictionio_tpu.analysis import tsan
from predictionio_tpu.utils import env as envmod


def run_lint(tmp_path, source, rules=None, name="mod.py"):
    p = tmp_path / name
    p.write_text(source)
    findings, errors = lint_mod.lint_paths([str(p)], rules)
    assert not errors, errors
    return findings


def rules_named(*names):
    by_name = {r.name: r for r in lint_mod.all_rules()}
    return [by_name[n] for n in names]


# ---------------------------------------------------------------------------
# thread-lifecycle
# ---------------------------------------------------------------------------

GOOD_THREAD = '''
import threading

class Worker:
    def __init__(self):
        self._thread = threading.Thread(
            target=self._loop, name="worker", daemon=True
        )

    def _loop(self):
        pass

    def stop(self):
        self._thread.join(timeout=5)
'''

BAD_THREAD_FIRE_AND_FORGET = '''
import threading

def kick():
    threading.Thread(target=print, name="oops", daemon=True).start()
'''

BAD_THREAD_NO_NAME = '''
import threading

class Worker:
    def __init__(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        pass

    def stop(self):
        self._thread.join()
'''

BAD_THREAD_NO_STOP = '''
import threading

class Worker:
    def __init__(self):
        self._thread = threading.Thread(
            target=print, name="w", daemon=True
        )
'''

GOOD_THREAD_LOCAL_JOIN = '''
import threading

def run():
    t = threading.Thread(target=print, name="t", daemon=True)
    t.start()
    t.join()
'''

GOOD_THREAD_TRACKED = '''
import threading

class Owner:
    def __init__(self):
        self._strays = []

    def fire(self):
        t = threading.Thread(target=print, name="s", daemon=True)
        self._strays.append(t)
        t.start()

    def stop(self):
        for t in self._strays:
            t.join()
'''


class TestThreadLifecycle:
    def test_owned_named_daemon_thread_is_clean(self, tmp_path):
        assert run_lint(tmp_path, GOOD_THREAD) == []

    def test_fire_and_forget_flagged(self, tmp_path):
        fs = run_lint(tmp_path, BAD_THREAD_FIRE_AND_FORGET)
        assert any(f.rule == "thread-lifecycle" for f in fs)
        assert any("fire-and-forget" in f.message for f in fs)

    def test_missing_name_flagged(self, tmp_path):
        fs = run_lint(tmp_path, BAD_THREAD_NO_NAME)
        assert any("without name=" in f.message for f in fs)

    def test_missing_stop_join_flagged(self, tmp_path):
        fs = run_lint(tmp_path, BAD_THREAD_NO_STOP)
        assert any("no stop()/join() path" in f.message for f in fs)

    def test_local_join_and_tracked_stray_are_clean(self, tmp_path):
        assert run_lint(tmp_path, GOOD_THREAD_LOCAL_JOIN) == []
        assert run_lint(tmp_path, GOOD_THREAD_TRACKED) == []

    def test_line_suppression(self, tmp_path):
        src = BAD_THREAD_FIRE_AND_FORGET.replace(
            'daemon=True).start()',
            'daemon=True).start()  # lint: disable=thread-lifecycle — x',
        )
        assert run_lint(tmp_path, src) == []

    def test_file_suppression(self, tmp_path):
        src = "# lint: disable=thread-lifecycle — test file\n" + (
            BAD_THREAD_FIRE_AND_FORGET
        )
        assert run_lint(tmp_path, src) == []


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------

GOOD_LOCKS = '''
import threading

class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}  # guarded-by: _lock

    def put(self, k, v):
        with self._lock:
            self._entries[k] = v

    def _evict_locked(self):  # lint: holds=_lock
        self._entries.clear()

    def read(self):
        return dict(self._entries)
'''

BAD_LOCKS = '''
import threading

class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}  # guarded-by: _lock

    def put(self, k, v):
        self._entries[k] = v

    def drop(self, k):
        self._entries.pop(k, None)

    def reset(self):
        self._entries = {}
'''

ALT_LOCKS = '''
import threading

class Q:
    def __init__(self):
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._items = []  # guarded-by: _lock|_not_empty

    def put(self, x):
        with self._not_empty:
            self._items.append(x)
'''


class TestLockDiscipline:
    def test_guarded_mutations_under_lock_are_clean(self, tmp_path):
        assert run_lint(tmp_path, GOOD_LOCKS) == []

    def test_unlocked_mutations_flagged(self, tmp_path):
        fs = run_lint(tmp_path, BAD_LOCKS)
        kinds = {f.message.split(" but ")[1].split(" outside")[0] for f in fs}
        assert len(fs) == 3  # item-assign, .pop(), rebind
        assert any("item-assigned" in k for k in kinds)
        assert any(".pop() called" in k for k in kinds)
        assert any("assigned" in k for k in kinds)

    def test_condition_alternative_lock_accepted(self, tmp_path):
        assert run_lint(tmp_path, ALT_LOCKS) == []

    def test_init_is_exempt(self, tmp_path):
        # the declaration itself is a mutation in __init__ — never flagged
        assert run_lint(tmp_path, GOOD_LOCKS, rules_named("lock-discipline")) == []


# ---------------------------------------------------------------------------
# env-knobs
# ---------------------------------------------------------------------------

class TestEnvKnobs:
    def test_raw_environ_read_flagged(self, tmp_path):
        fs = run_lint(tmp_path, 'import os\nx = os.environ.get("PIO_FOO")\n')
        assert any(f.rule == "env-knobs" for f in fs)

    def test_subscript_read_flagged(self, tmp_path):
        fs = run_lint(tmp_path, 'import os\nx = os.environ["PIO_FOO"]\n')
        assert any(f.rule == "env-knobs" for f in fs)

    def test_mapping_get_flagged(self, tmp_path):
        fs = run_lint(tmp_path, 'def f(env):\n    return env.get("PIO_X")\n')
        assert any("captured env mapping" in f.message for f in fs)

    def test_unregistered_parser_knob_flagged(self, tmp_path):
        src = (
            "from predictionio_tpu.utils.env import env_float\n"
            'x = env_float("PIO_NOT_A_KNOB", 1.0)\n'
        )
        fs = run_lint(tmp_path, src)
        assert any("not declared in the" in f.message for f in fs)

    def test_registered_parser_and_writes_are_clean(self, tmp_path):
        src = (
            "import os\n"
            "from predictionio_tpu.utils.env import env_float\n"
            'x = env_float("PIO_TRACE_SAMPLE")\n'
            'os.environ["PIO_TRACE_SAMPLE"] = "0.5"\n'  # writes allowed
            'os.environ.pop("PIO_TRACE_SAMPLE", None)\n'
            "y = dict(os.environ)\n"
        )
        assert run_lint(tmp_path, src) == []

    def test_prefix_family_accepted(self, tmp_path):
        src = (
            "from predictionio_tpu.utils.env import env_raw\n"
            'x = env_raw("PIO_STORAGE_SOURCES_PG_TYPE")\n'
        )
        assert run_lint(tmp_path, src) == []


class TestEnvRegistry:
    def test_typed_parsers(self, monkeypatch):
        monkeypatch.setenv("PIO_TRACE_MAX", "42")
        assert envmod.env_int("PIO_TRACE_MAX") == 42
        monkeypatch.setenv("PIO_TRACE_MAX", "nonsense")
        assert envmod.env_int("PIO_TRACE_MAX") == 256  # registry default
        monkeypatch.setenv("PIO_ROLLOUT_SHADOW", "false")
        assert envmod.env_bool("PIO_ROLLOUT_SHADOW") is False
        monkeypatch.setenv("PIO_ROLLOUT_SHADOW", "yes")
        assert envmod.env_bool("PIO_ROLLOUT_SHADOW") is True
        monkeypatch.delenv("PIO_DEVPROF", raising=False)
        assert envmod.env_bool("PIO_DEVPROF") is True  # flag default "1"
        monkeypatch.setenv("PIO_DEVPROF", "0")
        assert envmod.env_bool("PIO_DEVPROF") is False

    def test_env_mapping_parameter(self):
        env = {"PIO_ROLLOUT_BAKE_S": "5"}
        assert envmod.env_float("PIO_ROLLOUT_BAKE_S", env=env) == 5.0
        assert envmod.env_float("PIO_ROLLOUT_BAKE_S", env={}) == 60.0

    def test_unregistered_read_raises(self):
        with pytest.raises(ValueError, match="not declared"):
            envmod.env_str("PIO_TOTALLY_UNKNOWN")

    def test_prefix_lookup(self):
        assert envmod.env_raw(
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE", env={}
        ) is None

    def test_markdown_table_covers_registry(self):
        table = envmod.knobs_markdown()
        for knob in envmod.knob_registry():
            assert knob.name in table
        assert table.startswith("| Knob | Type | Default | Description |")


# ---------------------------------------------------------------------------
# jit-boundary
# ---------------------------------------------------------------------------

BAD_JIT = '''
import jax
from functools import partial

@jax.jit
def f(x):
    return x

@partial(jax.jit, static_argnames=("k",))
def g(x, *, k):
    return x
'''

GOOD_JIT = BAD_JIT + '''
from predictionio_tpu.obs import devprof as _devprof
f = _devprof.instrument("m.f", f)
g = _devprof.instrument("m.g", g)
'''

HOST_CALL_JIT = '''
import time
import jax
from predictionio_tpu.obs import devprof as _devprof

@jax.jit
def f(x):
    return x * time.time()

f = _devprof.instrument("m.f", f)
'''

BARE_PALLAS = '''
from jax.experimental import pallas as pl

def launch(x):
    return pl.pallas_call(lambda r: r)(x)
'''

JITTED_PALLAS = '''
import jax
from jax.experimental import pallas as pl
from predictionio_tpu.obs import devprof as _devprof

@jax.jit
def entry(x):
    return launch(x)

def launch(x):
    return pl.pallas_call(lambda r: r)(x)

entry = _devprof.instrument("m.entry", entry)
'''


class TestJitBoundary:
    def test_uninstrumented_jit_flagged(self, tmp_path):
        fs = run_lint(tmp_path, BAD_JIT)
        assert len([f for f in fs if f.rule == "jit-boundary"]) == 2

    def test_instrumented_jit_clean(self, tmp_path):
        assert run_lint(tmp_path, GOOD_JIT) == []

    def test_host_clock_inside_jit_flagged(self, tmp_path):
        fs = run_lint(tmp_path, HOST_CALL_JIT)
        assert any("time.time" in f.message for f in fs)

    def test_bare_pallas_launch_flagged(self, tmp_path):
        fs = run_lint(tmp_path, BARE_PALLAS)
        assert any("pallas_call" in f.message for f in fs)

    def test_pallas_under_jitted_entry_clean(self, tmp_path):
        assert run_lint(tmp_path, JITTED_PALLAS) == []


# ---------------------------------------------------------------------------
# metric-cardinality
# ---------------------------------------------------------------------------

BAD_METRIC_FAMILY = '''
def attach(registry):
    return registry.counter(
        "requests_total", "requests", ("route",),
    )
'''

GOOD_METRIC_FAMILY = '''
def attach(registry):
    return registry.counter(
        "requests_total", "requests",
        ("route",),  # label-bound: _route_label table
    )
'''

BAD_METRIC_FEED = '''
def count(counter, path):
    counter.inc(route=f"/api/{path}")
'''


class TestMetricCardinality:
    def test_unannotated_family_flagged(self, tmp_path):
        fs = run_lint(tmp_path, BAD_METRIC_FAMILY)
        assert any(f.rule == "metric-cardinality" for f in fs)

    def test_annotated_family_clean(self, tmp_path):
        assert run_lint(tmp_path, GOOD_METRIC_FAMILY) == []

    def test_constructed_label_value_flagged(self, tmp_path):
        fs = run_lint(tmp_path, BAD_METRIC_FEED)
        assert any("f-string" in f.message for f in fs)

    def test_unlabeled_family_ignored(self, tmp_path):
        src = 'def f(r):\n    return r.counter("a", "b")\n'
        assert run_lint(tmp_path, src) == []


# ---------------------------------------------------------------------------
# the gate: the real package lints clean
# ---------------------------------------------------------------------------

class TestRepoIsClean:
    def test_package_lints_clean_with_all_rules(self):
        findings, errors = lint_mod.lint_repo()
        assert errors == []
        assert findings == [], "\n".join(str(f) for f in findings)


# ---------------------------------------------------------------------------
# dynamic sanitizer
# ---------------------------------------------------------------------------

@pytest.fixture()
def san():
    tsan.reset()
    tsan.enable()
    try:
        yield tsan
    finally:
        tsan.disable()
        tsan.reset()


class TestTsan:
    def test_seeded_ab_ba_inversion_reports_cycle(self, san):
        lock_a = threading.Lock()
        lock_b = threading.Lock()
        with lock_a:
            with lock_b:
                pass
        with lock_b:
            with lock_a:
                pass
        rep = san.report(check_leaks=False)
        assert rep["lock_order_cycles"], rep
        cyc = rep["lock_order_cycles"][0]
        assert len(cyc["sites"]) == 2
        assert len(cyc["edges"]) == 2
        assert all(e["stack"] for e in cyc["edges"])

    def test_consistent_order_reports_no_cycle(self, san):
        lock_a = threading.Lock()
        lock_b = threading.Lock()
        for _ in range(3):
            with lock_a:
                with lock_b:
                    pass
        rep = san.report(check_leaks=False)
        assert rep["lock_order_cycles"] == []
        assert rep["edges_total"] == 1

    def test_rlock_reentrancy_records_no_self_edge(self, san):
        lk = threading.RLock()
        with lk:
            with lk:
                pass
        rep = san.report(check_leaks=False)
        assert rep["lock_order_cycles"] == []
        assert rep["edges_total"] == 0

    def test_note_blocking_flags_held_lock(self, san):
        lk = threading.Lock()
        with lk:
            san.note_blocking("device.dispatch")
        rep = san.report(check_leaks=False)
        assert rep["blocking_with_lock_held"], rep
        b = rep["blocking_with_lock_held"][0]
        assert b["kind"] == "device.dispatch"
        assert b["held_sites"]

    def test_note_blocking_without_lock_is_clean(self, san):
        san.note_blocking("storage.rpc")
        rep = san.report(check_leaks=False)
        assert rep["blocking_with_lock_held"] == []

    def test_allow_blocking_suppresses_declared_lock(self, san):
        lk = threading.Lock()
        san.allow_blocking("test_analysis.py")
        with lk:
            san.note_blocking("device.dispatch")
        rep = san.report(check_leaks=False)
        assert rep["blocking_with_lock_held"] == []

    def test_thread_leak_tripwire(self, san):
        release = threading.Event()
        t = threading.Thread(
            target=release.wait, name="leaky", daemon=True
        )
        t.start()
        leaked = [d["name"] for d in san.leaked_threads()]
        assert "leaky" in leaked
        release.set()
        t.join(timeout=5)
        assert "leaky" not in [d["name"] for d in san.leaked_threads()]

    def test_condition_compatibility(self, san):
        # FairQueue builds a Condition over a sanitized Lock — the whole
        # put/wait/get protocol must work through the proxy
        from predictionio_tpu.tenancy.fair import FairQueue

        q = FairQueue()

        class Item:
            tenant = None

        q.put(Item())
        got = q.get(timeout=2)
        assert got is not None
        rep = san.report(check_leaks=False)
        assert rep["lock_order_cycles"] == []

    def test_write_report(self, san, tmp_path):
        lk = threading.Lock()
        with lk:
            pass
        path = str(tmp_path / "rep.json")
        out = san.write_report(path, check_leaks=False)
        assert out == path
        rep = json.loads(open(path).read())
        assert rep["enabled"] is True
        assert "findings_count" in rep

    def test_disable_stops_recording(self):
        tsan.reset()
        tsan.enable()
        lk = threading.Lock()
        tsan.disable()
        try:
            other = threading.Lock()
            with lk:  # proxy survives disable; records nothing
                with other:
                    pass
            rep = tsan.report(check_leaks=False)
            assert rep["edges_total"] == 0
        finally:
            tsan.reset()


class TestBridgeRaceRegression:
    """ISSUE 12 lock-discipline find: SpanRecorder.bridge/unbridge
    mutated `_bridges` outside the recorder lock — unbridge's
    check-then-pop could tear down a NEWER server's bridge when a stop
    raced a registration. Both now run under the lock; hammer the
    interleaving to keep it that way."""

    def test_unbridge_does_not_drop_newer_registration(self):
        from predictionio_tpu.obs.spans import SpanRecorder

        rec = SpanRecorder(sample_rate=0.0)
        stop = threading.Event()
        errors = []

        def spanner():
            try:
                while not stop.is_set():
                    with rec.span("bridged.op"):
                        pass
            except Exception as e:  # pragma: no cover
                errors.append(e)

        t = threading.Thread(target=spanner, name="spanner", daemon=True)
        t.start()
        try:
            for _ in range(300):
                old = lambda sp: None  # noqa: E731
                new = lambda sp: None  # noqa: E731
                rec.bridge("bridged.op", old)
                rec.bridge("bridged.op", new)
                rec.unbridge("bridged.op", old)  # stale unbridge: no-op
                assert rec._bridges.get("bridged.op") is new
                rec.unbridge("bridged.op", new)
        finally:
            stop.set()
            t.join(timeout=5)
        assert errors == []


class TestNotifierJoinRegression:
    """ISSUE 12 thread-lifecycle find: alert delivery threads were
    fire-and-forget — a page in flight could outlive the SLO engine
    that raised it. close() must join them."""

    def test_close_joins_inflight_deliveries(self):
        from predictionio_tpu.obs.monitor.notify import AlertNotifier
        from predictionio_tpu.obs.registry import MetricsRegistry

        n = AlertNotifier(
            exec_cmd="sleep 0.2", registry=MetricsRegistry()
        )
        n.notify({"slo": "x", "transition": "inactive->firing"})
        assert any(
            t.name == "alert-notify" for t in threading.enumerate()
        )
        t0 = time.monotonic()
        n.close(timeout=10)
        assert time.monotonic() - t0 < 5
        assert not any(
            t.name == "alert-notify" and t.is_alive()
            for t in threading.enumerate()
        )


# ---------------------------------------------------------------------------
# console round-trip
# ---------------------------------------------------------------------------

class TestConsole:
    def test_lint_exit_codes(self, tmp_path, capsys):
        from predictionio_tpu.tools import console

        bad = tmp_path / "bad.py"
        bad.write_text(BAD_THREAD_FIRE_AND_FORGET)
        assert console.main(["lint", str(bad)]) == 1
        out = capsys.readouterr().out
        assert "thread-lifecycle" in out

        bad.write_text(GOOD_THREAD)
        assert console.main(["lint", str(bad)]) == 0

    def test_lint_rule_filter_and_json(self, tmp_path, capsys):
        from predictionio_tpu.tools import console

        bad = tmp_path / "bad.py"
        bad.write_text(BAD_THREAD_FIRE_AND_FORGET)
        rc = console.main(
            ["lint", "--rule", "env-knobs", str(bad)]
        )
        assert rc == 0  # thread finding filtered out
        capsys.readouterr()  # drain the first invocation's summary
        rc = console.main(["lint", "--json", str(bad)])
        assert rc == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload["findings"]
        assert console.main(["lint", "--rule", "bogus", str(bad)]) == 1

    def test_knobs_table(self, capsys):
        from predictionio_tpu.tools import console

        assert console.main(["lint", "--knobs"]) == 0
        out = capsys.readouterr().out
        assert "PIO_TSAN" in out and "PIO_FS_BASEDIR" in out

    def test_knobs_readme_freshness(self):
        import os

        from predictionio_tpu.tools import console

        readme = os.path.join(
            os.path.dirname(lint_mod.package_root()), "README.md"
        )
        assert console.main(
            ["lint", "--knobs", "--check-readme", readme]
        ) == 0

    def test_tsan_report_roundtrip(self, tmp_path, capsys):
        from predictionio_tpu.tools import console

        clean = tmp_path / "clean.json"
        clean.write_text(json.dumps({"findings_count": 0}))
        assert console.main(["lint", "--tsan-report", str(clean)]) == 0
        dirty = tmp_path / "dirty.json"
        dirty.write_text(json.dumps({
            "findings_count": 1,
            "lock_order_cycles": [{"sites": ["a", "b"], "edges": []}],
        }))
        assert console.main(["lint", "--tsan-report", str(dirty)]) == 1
