"""Per-shard devprof attribution (ISSUE 10) and the XLA-semantics
premise it rests on: shard_map programs lower the PER-DEVICE module, so
cost/memory analysis is already one shard's share and the report must
NOT divide again."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

if len(jax.devices()) < 8:  # pragma: no cover - env guard
    pytest.skip("needs 8 devices", allow_module_level=True)


def test_shard_map_cost_analysis_is_per_device():
    """The measured premise: a shard_map'd matmul's cost analysis
    reports the local (per-device) FLOPs, not the global program's."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from predictionio_tpu.parallel.mesh import shard_map

    mesh = Mesh(np.array(jax.devices()), ("s",))
    a = jax.device_put(
        np.ones((1024, 512), np.float32), NamedSharding(mesh, P("s", None))
    )
    b = jax.device_put(
        np.ones((512, 256), np.float32), NamedSharding(mesh, P())
    )
    f = jax.jit(shard_map(
        lambda x, y: x @ y, mesh=mesh,
        in_specs=(P("s", None), P()), out_specs=P("s", None),
    ))
    ca = f.lower(a, b).cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    flops = float(ca.get("flops", 0.0))
    global_flops = 2 * 1024 * 512 * 256
    # per-device ±1% (XLA counts a few scalar ops besides the matmul)
    assert abs(flops - global_flops / 8) < 0.01 * global_flops, flops


def test_report_emits_devices_without_double_division():
    from predictionio_tpu.fleet import ShardedRuntime
    from predictionio_tpu.obs.devprof import get_profiler

    rng = np.random.RandomState(0)
    srt = ShardedRuntime(
        rng.randn(64, 8).astype(np.float32),
        rng.randn(48, 8).astype(np.float32),
    )
    srt.recommend(np.arange(4), 5)
    row = get_profiler().executable("fleet.recommend_sharded")
    assert row is not None
    assert row.get("devices") == 8.0
    # the per-device memory-analysis sizes pass through undivided
    if row.get("memory_analysis_ok"):
        assert row["hbm_bytes_per_shard"] == pytest.approx(
            row["argument_bytes"] + row["output_bytes"]
            + row["temp_bytes"]
        )
    # the removed double-divided fields must not come back
    assert "flops_per_call_per_shard" not in row
    assert "mfu_per_shard" not in row
