"""CLI tests — in-process main(argv) against an injected Storage (the
black-box shell tests of the reference live in test_console_sh via
subprocess; these cover command logic + output)."""

import json

import pytest

from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.tools.console import main


@pytest.fixture()
def cli(fresh_storage, monkeypatch):
    Storage.set_instance(fresh_storage)
    yield lambda *argv: main(list(argv))
    Storage.set_instance(None)


def test_app_lifecycle(cli, capsys):
    assert cli("app", "new", "myapp", "--access-key", "SECRET") == 0
    out = capsys.readouterr().out
    assert "App created" in out and "SECRET" in out

    assert cli("app", "new", "myapp") == 1  # duplicate

    assert cli("app", "list") == 0
    assert "myapp" in capsys.readouterr().out

    assert cli("app", "show", "myapp") == 0
    assert "SECRET" in capsys.readouterr().out

    assert cli("app", "delete", "myapp", "-f") == 0
    assert cli("app", "show", "myapp") == 1


def test_channel_and_accesskey(cli, capsys):
    cli("app", "new", "chapp")
    capsys.readouterr()
    assert cli("channel", "new", "chapp", "live") == 0
    assert cli("channel", "new", "chapp", "bad name!") == 1
    assert cli("accesskey", "new", "chapp", "--key", "K2", "--events", "rate,buy") == 0
    capsys.readouterr()
    assert cli("accesskey", "list", "chapp") == 0
    out = capsys.readouterr().out
    assert "K2" in out and "rate,buy" in out
    assert cli("accesskey", "delete", "K2") == 0
    assert cli("accesskey", "delete", "K2") == 1
    assert cli("channel", "delete", "chapp", "live") == 0
    assert cli("channel", "delete", "chapp", "live") == 1


def test_train_from_cli(cli, tmp_path, capsys):
    variant = {
        "id": "cli-test",
        "engineFactory": "sample_engine.Engine0Factory",
        "datasource": {"params": {"id": 1}},
        "preparator": {"params": {"id": 2}},
        "algorithms": [{"name": "algo0", "params": {"id": 3}}],
    }
    path = tmp_path / "engine.json"
    path.write_text(json.dumps(variant))
    assert cli("train", "--engine-json", str(path)) == 0
    assert "Training completed" in capsys.readouterr().out

    # stop-after-read is a clean interrupted stop, not a failure
    assert cli("train", "--engine-json", str(path), "--stop-after-read") == 0
    assert "interrupted" in capsys.readouterr().out.lower()


def test_status(cli, capsys):
    assert cli("status") == 0
    out = capsys.readouterr().out
    assert "ready to go" in out


def test_export_import_roundtrip(cli, tmp_path, capsys):
    cli("app", "new", "exapp")
    capsys.readouterr()
    # import some events
    src = tmp_path / "in.jsonl"
    lines = [
        {"event": "rate", "entityType": "user", "entityId": f"u{i}",
         "targetEntityType": "item", "targetEntityId": "i1",
         "properties": {"rating": 5}}
        for i in range(4)
    ]
    lines.append({"event": "$bad", "entityType": "user", "entityId": "x"})
    src.write_text("\n".join(json.dumps(l) for l in lines))
    assert cli("import", "--app", "exapp", "--input", str(src)) == 1  # 1 bad line
    assert "Imported 4 events" in capsys.readouterr().out

    dst = tmp_path / "out.jsonl"
    assert cli("export", "--app", "exapp", "--output", str(dst)) == 0
    exported = [json.loads(l) for l in dst.read_text().splitlines()]
    assert len(exported) == 4
    assert {e["entityId"] for e in exported} == {f"u{i}" for i in range(4)}


def test_export_import_parquet_roundtrip(cli, tmp_path, capsys):
    """`pio export --format parquet` (EventsToFile.scala:42 parity) and
    the parquet import round trip."""
    cli("app", "new", "pqapp")
    capsys.readouterr()
    src = tmp_path / "events.jsonl"
    src.write_text(
        "\n".join(
            json.dumps(
                {
                    "event": "rate",
                    "entityType": "user",
                    "entityId": f"u{i}",
                    "targetEntityType": "item",
                    "targetEntityId": f"i{i % 3}",
                    "properties": {"rating": float(i % 5 + 1)},
                    "eventTime": f"2026-01-0{i + 1}T00:00:00.000Z",
                }
            )
            for i in range(5)
        )
    )
    assert cli("import", "--app", "pqapp", "--input", str(src)) == 0
    capsys.readouterr()

    pq_out = tmp_path / "events.parquet"
    assert (
        cli("export", "--app", "pqapp", "--output", str(pq_out),
            "--format", "parquet") == 0
    )
    assert "Exported 5 events" in capsys.readouterr().out
    import pyarrow.parquet as pq

    table = pq.read_table(pq_out)
    assert table.num_rows == 5
    assert "properties" in table.schema.names

    # round trip into a second app: same events come back
    cli("app", "new", "pqapp2")
    capsys.readouterr()
    assert cli("import", "--app", "pqapp2", "--input", str(pq_out)) == 0
    json_out = tmp_path / "roundtrip.jsonl"
    assert cli("export", "--app", "pqapp2", "--output", str(json_out)) == 0
    back = [json.loads(l) for l in json_out.read_text().splitlines()]
    assert len(back) == 5
    assert {b["entityId"] for b in back} == {f"u{i}" for i in range(5)}
    assert all("rating" in b["properties"] for b in back)


def test_pio_shell_namespace_and_piped_exec(fresh_storage, tmp_path, capsys):
    """pio-shell (reference bin/pio-shell role): preloaded namespace over
    the configured storage; piped stdin executes in it."""
    from predictionio_tpu.data.storage.registry import Storage
    from predictionio_tpu.tools import shell

    Storage.set_instance(fresh_storage)
    try:
        ns = shell.make_namespace()
        assert {"storage", "events", "facade", "Event", "EventQuery"} <= set(ns)
        # the namespace is live: write through it, read back through it
        ev = ns["Event"](
            event="$set", entity_type="user", entity_id="u1",
            properties={"plan": "pro"},
        )
        ns["events"].init_app(1)
        ns["events"].insert(ev, 1)
        got = list(ns["events"].find(ns["EventQuery"](app_id=1)))
        assert len(got) == 1 and got[0].entity_id == "u1"
        ns["help_pio"]()
        assert "storage" in capsys.readouterr().out
    finally:
        Storage.set_instance(None)


def test_pio_shell_script_subprocess(tmp_path):
    """bin/pio-shell end to end: piped script runs with the framework
    preloaded against env-configured storage."""
    import os
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": str(repo) + os.pathsep + env.get("PYTHONPATH", ""),
        "PIO_STORAGE_SOURCES_T_TYPE": "sqlite",
        "PIO_STORAGE_SOURCES_T_PATH": str(tmp_path / "pio.db"),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "T",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "T",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "T",
    })
    script = (
        "events.init_app(1)\n"
        "events.insert(Event(event='buy', entity_type='user',"
        " entity_id='u9'), 1)\n"
        "print('GOT', len(list(events.find(EventQuery(app_id=1)))))\n"
    )
    r = subprocess.run(
        [str(repo / "bin" / "pio-shell")],
        input=script, env=env, capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "GOT 1" in r.stdout


def test_faults_cli_local_registry(cli, capsys):
    """`pio faults set|list|clear` drives the in-process fault registry
    (ISSUE 4 tooling satellite)."""
    from predictionio_tpu.resilience import faults

    try:
        assert cli("faults", "list") == 0
        assert "inert" in capsys.readouterr().out
        assert cli(
            "faults", "set", "storage.rpc:error:0.25", "--seed", "11"
        ) == 0
        out = capsys.readouterr().out
        assert "storage.rpc: error p=0.25" in out and "seed=11" in out
        assert {s["point"] for s in faults.specs()} == {"storage.rpc"}
        assert cli("faults", "set", "bogus.point:error:1.0") == 1  # loud
        capsys.readouterr()
        assert cli("faults", "clear", "storage.rpc") == 0
        assert "inert" in capsys.readouterr().out
        assert not faults.active()
    finally:
        faults.clear()


def test_tenants_cli(fresh_storage, capsys):
    """`pio tenants new|list|show|set-quota|delete` round trip."""
    from predictionio_tpu.data.storage.registry import Storage
    from predictionio_tpu.tools import console

    Storage.set_instance(fresh_storage)
    try:
        assert console.main([
            "tenants", "new", "acme", "--engine", "rec",
            "--weight", "2", "--qps", "100",
        ]) == 0
        assert console.main(["tenants", "list"]) == 0
        out = capsys.readouterr().out
        assert "acme" in out and "weight=2.0" in out
        assert console.main([
            "tenants", "set-quota", "acme", "--qps", "10",
            "--max-concurrency", "4",
        ]) == 0
        assert console.main(["tenants", "show", "acme"]) == 0
        out = capsys.readouterr().out
        assert '"qps": 10.0' in out
        assert console.main(["tenants", "delete", "acme"]) == 0
        assert console.main(["tenants", "show", "acme"]) == 1
    finally:
        Storage.set_instance(None)
