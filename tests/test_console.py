"""CLI tests — in-process main(argv) against an injected Storage (the
black-box shell tests of the reference live in test_console_sh via
subprocess; these cover command logic + output)."""

import json

import pytest

from predictionio_tpu.data.storage.registry import Storage
from predictionio_tpu.tools.console import main


@pytest.fixture()
def cli(fresh_storage, monkeypatch):
    Storage.set_instance(fresh_storage)
    yield lambda *argv: main(list(argv))
    Storage.set_instance(None)


def test_app_lifecycle(cli, capsys):
    assert cli("app", "new", "myapp", "--access-key", "SECRET") == 0
    out = capsys.readouterr().out
    assert "App created" in out and "SECRET" in out

    assert cli("app", "new", "myapp") == 1  # duplicate

    assert cli("app", "list") == 0
    assert "myapp" in capsys.readouterr().out

    assert cli("app", "show", "myapp") == 0
    assert "SECRET" in capsys.readouterr().out

    assert cli("app", "delete", "myapp", "-f") == 0
    assert cli("app", "show", "myapp") == 1


def test_channel_and_accesskey(cli, capsys):
    cli("app", "new", "chapp")
    capsys.readouterr()
    assert cli("channel", "new", "chapp", "live") == 0
    assert cli("channel", "new", "chapp", "bad name!") == 1
    assert cli("accesskey", "new", "chapp", "--key", "K2", "--events", "rate,buy") == 0
    capsys.readouterr()
    assert cli("accesskey", "list", "chapp") == 0
    out = capsys.readouterr().out
    assert "K2" in out and "rate,buy" in out
    assert cli("accesskey", "delete", "K2") == 0
    assert cli("accesskey", "delete", "K2") == 1
    assert cli("channel", "delete", "chapp", "live") == 0
    assert cli("channel", "delete", "chapp", "live") == 1


def test_train_from_cli(cli, tmp_path, capsys):
    variant = {
        "id": "cli-test",
        "engineFactory": "sample_engine.Engine0Factory",
        "datasource": {"params": {"id": 1}},
        "preparator": {"params": {"id": 2}},
        "algorithms": [{"name": "algo0", "params": {"id": 3}}],
    }
    path = tmp_path / "engine.json"
    path.write_text(json.dumps(variant))
    assert cli("train", "--engine-json", str(path)) == 0
    assert "Training completed" in capsys.readouterr().out

    # stop-after-read is a clean interrupted stop, not a failure
    assert cli("train", "--engine-json", str(path), "--stop-after-read") == 0
    assert "interrupted" in capsys.readouterr().out.lower()


def test_status(cli, capsys):
    assert cli("status") == 0
    out = capsys.readouterr().out
    assert "ready to go" in out


def test_export_import_roundtrip(cli, tmp_path, capsys):
    cli("app", "new", "exapp")
    capsys.readouterr()
    # import some events
    src = tmp_path / "in.jsonl"
    lines = [
        {"event": "rate", "entityType": "user", "entityId": f"u{i}",
         "targetEntityType": "item", "targetEntityId": "i1",
         "properties": {"rating": 5}}
        for i in range(4)
    ]
    lines.append({"event": "$bad", "entityType": "user", "entityId": "x"})
    src.write_text("\n".join(json.dumps(l) for l in lines))
    assert cli("import", "--app", "exapp", "--input", str(src)) == 1  # 1 bad line
    assert "Imported 4 events" in capsys.readouterr().out

    dst = tmp_path / "out.jsonl"
    assert cli("export", "--app", "exapp", "--output", str(dst)) == 0
    exported = [json.loads(l) for l in dst.read_text().splitlines()]
    assert len(exported) == 4
    assert {e["entityId"] for e in exported} == {f"u{i}" for i in range(4)}
