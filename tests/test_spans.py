"""Span recorder (ISSUE 2): hierarchy + context propagation, tail-based
sampling retention rules, thread-safety under concurrent traces, the
span→metric bridge, Perfetto export validity, and the route-label
cardinality guard."""

import json
import threading

import pytest

from predictionio_tpu.obs.registry import MetricsRegistry
from predictionio_tpu.obs.spans import Span, SpanRecorder, new_span_id
from predictionio_tpu.obs.tracing import trace_context


@pytest.fixture()
def recorder():
    return SpanRecorder(max_traces=16, slow_ms=10_000, sample_rate=1.0)


def test_hierarchy_and_trace_context(recorder):
    with trace_context("t-1"):
        with recorder.span("root", server="x") as root:
            with recorder.span("child") as child:
                with recorder.span("grandchild") as grand:
                    pass
            with recorder.span("sibling") as sib:
                pass
    spans = {s.name: s for s in recorder.get_trace("t-1")}
    assert set(spans) == {"root", "child", "grandchild", "sibling"}
    assert root.trace_id == "t-1"
    assert spans["root"].parent_span_id is None
    assert spans["child"].parent_span_id == root.span_id
    assert spans["grandchild"].parent_span_id == child.span_id
    assert spans["sibling"].parent_span_id == root.span_id
    assert grand.duration >= 0 and sib.duration >= 0
    summary = recorder.summaries()[0]
    assert summary["trace_id"] == "t-1"
    assert summary["root"] == "root"
    assert summary["spans"] == 4


def test_span_without_trace_mints_one(recorder):
    with recorder.span("lonely") as sp:
        pass
    assert sp.trace_id
    assert recorder.get_trace(sp.trace_id)[0].name == "lonely"


def test_explicit_trace_id_flows_to_children(recorder):
    """A span opened with trace_id=... must establish trace context for
    everything nested, exactly like an inherited ambient trace."""
    with recorder.span("root", trace_id="t-explicit") as root:
        with recorder.span("child") as child:
            pass
    assert child.trace_id == "t-explicit"
    assert child.parent_span_id == root.span_id
    assert len(recorder.get_trace("t-explicit")) == 2


def test_error_marks_span_and_reraises(recorder):
    with pytest.raises(ValueError):
        with recorder.span("boom", trace_id="t-err"):
            raise ValueError("nope")
    spans = recorder.get_trace("t-err")
    assert spans and spans[0].error
    assert recorder.summaries()[0]["error"]


# -- tail-based sampling ----------------------------------------------------


def test_tail_sampling_drops_boring_keeps_error_and_slow():
    rec = SpanRecorder(max_traces=16, slow_ms=50, sample_rate=0.0)
    # boring: fast, no error, sample_rate 0 → dropped
    with rec.span("fast", trace_id="t-boring"):
        pass
    assert rec.get_trace("t-boring") == []
    # errored → always kept
    with pytest.raises(RuntimeError):
        with rec.span("fails", trace_id="t-err"):
            raise RuntimeError("x")
    assert rec.summaries()[0]["kept"] == "error"
    # slow (≥ slow_ms, via a manually recorded duration) → always kept,
    # even when the SLOW span is a child and the root itself is fast
    rec.record(Span(
        trace_id="t-slow", span_id=new_span_id(), name="slow.child",
        start=0.0, duration=0.120,
    ))
    rec.record(Span(
        trace_id="t-slow", span_id=new_span_id(), name="root",
        start=0.0, duration=0.001,
    ), finalize=True)
    kept = {s["trace_id"]: s for s in rec.summaries()}
    assert kept["t-slow"]["kept"] == "slow"
    assert "t-boring" not in kept


def test_retention_cap_evicts_oldest():
    rec = SpanRecorder(max_traces=4, slow_ms=10_000, sample_rate=1.0)
    for i in range(10):
        with rec.span("r", trace_id=f"t-{i}"):
            pass
    kept = [s["trace_id"] for s in rec.summaries()]
    assert len(kept) == 4
    assert set(kept) == {"t-6", "t-7", "t-8", "t-9"}  # oldest evicted


def test_reused_trace_id_is_capped_and_still_ages_out():
    """X-Request-ID is client-controlled: one id replayed forever must
    neither grow a retained trace unbounded nor pin it against
    eviction."""
    rec = SpanRecorder(max_traces=4, slow_ms=10_000, sample_rate=1.0)
    rec.max_spans_per_trace = 10
    with rec.span("r", trace_id="t-pinned"):
        pass
    for _ in range(50):  # replayed id: merge path
        with rec.span("r", trace_id="t-pinned"):
            pass
    assert len(rec.get_trace("t-pinned")) == 10  # capped
    for i in range(4):  # fresh traces evict the pinned one despite merges
        with rec.span("r", trace_id=f"t-new-{i}"):
            pass
    assert rec.get_trace("t-pinned") == []


def test_unbridge_only_removes_own_callback(recorder):
    reg = MetricsRegistry()
    h1 = reg.histogram("h1_seconds", "")
    h2 = reg.histogram("h2_seconds", "")
    cb1 = lambda sp: h1.observe(sp.duration)  # noqa: E731
    cb2 = lambda sp: h2.observe(sp.duration)  # noqa: E731
    recorder.bridge("x", cb1)
    recorder.bridge("x", cb2)  # newer server wins
    recorder.unbridge("x", cb1)  # stale server's teardown: no-op
    with recorder.span("x", trace_id="t-u1"):
        pass
    assert h2.count == 1 and h1.count == 0
    recorder.unbridge("x", cb2)
    with recorder.span("x", trace_id="t-u2"):
        pass
    assert h2.count == 1  # removed


def test_remote_rooted_fragment_defers_instead_of_dropping():
    """Two servers in one process: the inner daemon's server span (which
    has a REMOTE parent) finalizes mid-request. With sampling that would
    drop it, the fragment must be deferred — not discarded — so the
    outer request's eventual slow/error keep decision sees the full
    union, queue/assemble spans included."""
    rec = SpanRecorder(max_traces=16, slow_ms=100, sample_rate=0.0)
    rec.record(Span(
        trace_id="t-d", span_id="early", parent_span_id="root-id",
        name="batch.queue_wait", start=0.0, duration=0.001,
    ))
    rec.record(Span(
        trace_id="t-d", span_id="daemon", parent_span_id="rpc-id",
        name="server.request", start=0.0, duration=0.001,
    ), finalize=True)
    assert rec.get_trace("t-d") == []  # deferred, not retained yet
    rec.record(Span(  # true root (no parent at all), slow → keep union
        trace_id="t-d", span_id="root-id", parent_span_id=None,
        name="server.request", start=0.0, duration=0.5,
    ), finalize=True)
    assert {s.span_id for s in rec.get_trace("t-d")} == {
        "early", "daemon", "root-id",
    }
    assert rec.summaries()[0]["kept"] == "slow"
    # a TRUE-rooted boring trace still drops definitively
    rec.record(Span(
        trace_id="t-gone", span_id="r2", parent_span_id=None,
        name="storage.rpc", start=0.0, duration=0.001,
    ), finalize=True)
    assert rec.get_trace("t-gone") == []


def test_late_fragment_merges_into_kept_trace(recorder):
    """Cross-process shape: the remote fragment finalizes first, the
    client span arrives after — it must join the kept trace, not strand
    in the active map."""
    with recorder.span("server.request", trace_id="t-m"):
        pass
    recorder.record(Span(
        trace_id="t-m", span_id=new_span_id(), name="storage.rpc",
        start=0.0, duration=0.002,
    ))
    assert {s.name for s in recorder.get_trace("t-m")} == {
        "server.request", "storage.rpc",
    }


# -- concurrency ------------------------------------------------------------


def test_concurrent_traces_no_cross_request_leakage():
    """Hammer the recorder from N threads, each running M sequential
    traces with nested spans (the keep-alive handler-thread shape):
    every trace must keep exactly its own spans with correct parent
    links, and no span may leak into a sibling thread's trace."""
    rec = SpanRecorder(max_traces=1000, slow_ms=10_000, sample_rate=1.0)
    n_threads, n_traces = 8, 25
    errors: list[str] = []

    def worker(w: int) -> None:
        for i in range(n_traces):
            tid = f"t-{w}-{i}"
            with trace_context(tid):
                with rec.span("root", worker=w, i=i) as root:
                    with rec.span("mid") as mid:
                        with rec.span("leaf"):
                            pass
                if root.trace_id != tid or mid.trace_id != tid:
                    errors.append(f"{tid}: wrong trace id")

    threads = [
        threading.Thread(target=worker, args=(w,)) for w in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    for w in range(n_threads):
        for i in range(n_traces):
            tid = f"t-{w}-{i}"
            spans = {s.name: s for s in rec.get_trace(tid)}
            assert set(spans) == {"root", "mid", "leaf"}, (tid, spans)
            assert all(s.trace_id == tid for s in spans.values())
            assert spans["root"].parent_span_id is None
            assert spans["mid"].parent_span_id == spans["root"].span_id
            assert spans["leaf"].parent_span_id == spans["mid"].span_id
            assert spans["root"].attrs == {"worker": w, "i": i}


# -- metric bridge ----------------------------------------------------------


def test_metric_bridge_feeds_histogram(recorder):
    reg = MetricsRegistry()
    hist = reg.histogram("bridged_seconds", "from spans")
    recorder.bridge("stage.x", lambda sp: hist.observe(sp.duration))
    for _ in range(3):
        with recorder.span("stage.x", trace_id="t-b"):
            pass
    with recorder.span("stage.other", trace_id="t-b2"):
        pass
    assert hist.count == 3  # only the declared name feeds it
    assert hist.sum >= 0


def test_bridge_exception_never_breaks_recording(recorder):
    def bad(sp):
        raise RuntimeError("metrics hiccup")

    recorder.bridge("fragile", bad)
    with recorder.span("fragile", trace_id="t-f"):
        pass
    assert recorder.get_trace("t-f")  # span recorded despite bridge error


# -- perfetto export --------------------------------------------------------


def test_perfetto_export_is_valid_chrome_trace_json(recorder):
    with trace_context("t-p"):
        with recorder.span("server.request", server="query", path="/q") as r:
            with recorder.span("batch.device_dispatch", server="query"):
                with recorder.span(
                    "storage.rpc", server="storage-client", dao="events"
                ):
                    pass
    export = recorder.perfetto_export("t-p")
    # round-trips as JSON and has the Chrome trace-event shape
    parsed = json.loads(json.dumps(export))
    events = parsed["traceEvents"]
    assert events
    x_events = [e for e in events if e["ph"] == "X"]
    assert len(x_events) == 3
    for e in events:
        assert e["ph"] in ("X", "M")
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        if e["ph"] == "X":
            assert isinstance(e["ts"], (int, float))
            assert isinstance(e["dur"], (int, float))
            assert e["args"]["trace_id"] == "t-p"
    # span depth maps to tid so children nest under parents
    by_name = {e["name"]: e for e in x_events}
    assert by_name["server.request"]["tid"] == 0
    assert by_name["batch.device_dispatch"]["tid"] == 1
    assert by_name["storage.rpc"]["tid"] == 2
    # each originating server gets a named process row
    procs = {e["args"]["name"] for e in events if e["ph"] == "M"}
    assert {"query", "storage-client"} <= procs
    assert by_name["server.request"]["args"]["span_id"] == r.span_id


def test_perfetto_export_all_and_missing(recorder):
    assert recorder.perfetto_export("nope")["traceEvents"] == []
    with recorder.span("a", trace_id="t-1"):
        pass
    with recorder.span("b", trace_id="t-2"):
        pass
    events = recorder.perfetto_export()["traceEvents"]
    assert {e["args"]["trace_id"] for e in events if e["ph"] == "X"} == {
        "t-1", "t-2",
    }


# -- route-label cardinality guard (satellite) ------------------------------


def test_route_label_cardinality_bounded():
    """Replay a scan of distinct per-entity paths and assert the metric
    label set stays bounded: every id/name segment collapses."""
    from predictionio_tpu.utils.http import JsonHandler

    label = lambda p: JsonHandler._route_label(None, p)  # noqa: E731
    paths = []
    for i in range(50):
        paths += [
            f"/events/ev-{i}.json",
            f"/events/ev-{i}",
            f"/engine_instances/inst-{i}.html",
            f"/engine_instances/inst-{i}.json",
            f"/engine_instances/inst-{i}",
            f"/cmd/app/app-{i}",
            f"/cmd/app/app-{i}/data",
            f"/cmd/channel/ch-{i}",
            f"/cmd/accesskey/key-{i}",
            f"/tenants/tenant-{i}",
            f"/tenants/tenant-{i}/queries.json",
            f"/tenants/tenant-{i}/rollout/start",
            f"/tenants/tenant-{i}/quota",
        ]
    labels = {label(p) for p in paths}
    assert labels == {
        "/events/{id}.json",
        "/events/{id}",
        "/engine_instances/{id}.html",
        "/engine_instances/{id}.json",
        "/engine_instances/{id}",
        "/cmd/app/{name}",
        "/cmd/app/{name}/data",
        "/cmd/channel/{name}",
        "/cmd/accesskey/{name}",
        "/tenants/{id}",
        "/tenants/{id}/queries.json",
        "/tenants/{id}/rollout/start",
        "/tenants/{id}/quota",
    }
    # non-entity routes pass through untouched
    assert label("/queries.json") == "/queries.json"
    assert label("/cmd/app") == "/cmd/app"
    assert label("/metrics") == "/metrics"
